"""ctypes bridge to the native Prometheus-matrix parser (`native/fastsamples.cpp`).

Loads ``libfastsamples.so``, building it with g++ on first use if missing
(cached next to the source; falls back silently to the pure-Python parser when
no compiler is available — the native path is an optimization, not a
requirement). ``parse_matrix`` has the same contract as the Python fallback:
response bytes → list of ((pod, container), float64 samples). The key is the
series' ``pod``/``container`` label pair — either component is ``""`` when the
query's grouping omits that label (per-workload queries group by pod only;
namespace-batched queries group by both).
"""

from __future__ import annotations

import ctypes
import json
import os
import struct
import subprocess
import threading
from typing import Optional

import numpy as np

#: Where the C++ source lives: the repo checkout layout by default,
#: overridable for installed deployments whose site-packages copy has no
#: sibling ``native/`` directory (e.g. a pip-installed console script).
_NATIVE_DIR = os.environ.get(
    "KRR_TPU_NATIVE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "native"),
)
_SO_PATH = os.path.join(_NATIVE_DIR, "libfastsamples.so")

_lib: Optional[ctypes.CDLL] = None
_lib_lock = threading.Lock()
_build_failed = False


def _load_library() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            sources = [
                os.path.join(_NATIVE_DIR, name)
                for name in ("fastsamples.cpp", "faststream.cpp")
            ]
            source = sources[0]
            # Rebuild when missing OR stale: a cached .so from an older source
            # would load but lack newer symbols, and the blanket failure
            # handling below would then silently disable the whole native
            # path. Staleness covers the headers too (pow10_table.h) — a
            # regenerated table with an untouched .cpp must also rebuild.
            inputs = [
                os.path.join(_NATIVE_DIR, f)
                for f in os.listdir(_NATIVE_DIR)
                if f.endswith((".cpp", ".h"))
            ] if os.path.isdir(_NATIVE_DIR) else []
            if not os.path.exists(_SO_PATH) or (
                inputs and max(map(os.path.getmtime, inputs)) > os.path.getmtime(_SO_PATH)
            ):
                if not os.path.exists(source):
                    raise FileNotFoundError(source)
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-o", _SO_PATH, *sources],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            lib = ctypes.CDLL(_SO_PATH)
            lib.krr_parse_matrix.restype = ctypes.c_long
            lib.krr_parse_matrix.argtypes = [
                ctypes.c_char_p,
                ctypes.c_long,
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_long,
                ctypes.POINTER(ctypes.c_long),
                ctypes.c_long,
                ctypes.c_char_p,
                ctypes.c_long,
            ]
            lib.krr_parse_matrix_digest.restype = ctypes.c_long
            lib.krr_parse_matrix_digest.argtypes = [
                ctypes.c_char_p,
                ctypes.c_long,
                ctypes.c_double,
                ctypes.c_double,
                ctypes.c_long,
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_long,
                ctypes.c_char_p,
                ctypes.c_long,
            ]
            lib.krr_parse_matrix_stats.restype = ctypes.c_long
            lib.krr_parse_matrix_stats.argtypes = [
                ctypes.c_char_p,
                ctypes.c_long,
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_long,
                ctypes.c_char_p,
                ctypes.c_long,
            ]
            lib.krr_count_series.restype = ctypes.c_long
            lib.krr_count_series.argtypes = [ctypes.c_char_p, ctypes.c_long]
            lib.krr_stream_new.restype = ctypes.c_void_p
            lib.krr_stream_new.argtypes = [ctypes.c_double, ctypes.c_double, ctypes.c_long]
            lib.krr_stream_feed.restype = ctypes.c_long
            lib.krr_stream_feed.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long]
            lib.krr_stream_finish.restype = ctypes.c_long
            lib.krr_stream_finish.argtypes = [ctypes.c_void_p]
            lib.krr_stream_names_len.restype = ctypes.c_long
            lib.krr_stream_names_len.argtypes = [ctypes.c_void_p]
            lib.krr_stream_read.restype = ctypes.c_long
            lib.krr_stream_read.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                ctypes.c_long,
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_long,
            ]
            lib.krr_stream_free.restype = None
            lib.krr_stream_free.argtypes = [ctypes.c_void_p]
            lib.krr_stream_reserve.restype = ctypes.c_long
            lib.krr_stream_reserve.argtypes = [ctypes.c_void_p, ctypes.c_long]
            lib.krr_stream_fold_into.restype = ctypes.c_long
            lib.krr_stream_fold_into.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_long),
                ctypes.c_long,
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_long,
            ]
            lib.krr_rw_uncompressed_len.restype = ctypes.c_longlong
            lib.krr_rw_uncompressed_len.argtypes = [ctypes.c_char_p, ctypes.c_longlong]
            lib.krr_rw_decode.restype = ctypes.c_longlong
            lib.krr_rw_decode.argtypes = [
                ctypes.c_char_p,
                ctypes.c_longlong,
                ctypes.c_longlong,
                ctypes.c_char_p,
                ctypes.c_longlong,
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_longlong),
                ctypes.c_longlong,
                ctypes.POINTER(ctypes.c_longlong),
                ctypes.c_longlong,
                ctypes.POINTER(ctypes.c_longlong),
                ctypes.POINTER(ctypes.c_longlong),
            ]
            lib.krr_digest_array.restype = ctypes.c_longlong
            lib.krr_digest_array.argtypes = [
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_longlong,
                ctypes.c_double,
                ctypes.c_double,
                ctypes.c_longlong,
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double),
            ]
            _lib = lib
        except Exception as e:
            _build_failed = True
            # One-time notice: the pure-Python fallback is correct but ~20x
            # slower, and silence here has historically hidden deployment
            # mistakes (missing source dir, stale .so, no compiler).
            import logging

            logging.getLogger("krr_tpu").info(
                "native parser unavailable (%s: %s) — using the pure-Python parser; "
                "set KRR_TPU_NATIVE_DIR to the directory holding fastsamples.cpp to enable it",
                type(e).__name__,
                e,
            )
    return _lib


#: Series identity: the (pod, container) label pair — or, on multi-namespace
#: coalesced queries whose grouping includes the namespace label,
#: (pod, container, namespace). Either of the first two components is ""
#: when the query's grouping omits that label; the namespace component is
#: present exactly when the response carried a non-empty namespace label, so
#: single-namespace queries keep their historical 2-tuple keys.
SeriesKey = tuple[str, ...]


def parse_matrix_python(body: bytes) -> list[tuple[SeriesKey, np.ndarray]]:
    """Reference implementation: json.loads + per-sample float().

    Raises on a non-success or shape-less payload (e.g. a proxy answering 200
    with ``{"status":"error"}``) so misconfigurations surface as logged query
    failures instead of silent empty histories."""
    payload = json.loads(body)
    if payload.get("status") != "success" or "result" not in payload.get("data", {}):
        raise ValueError(
            f"unexpected Prometheus response: status={payload.get('status')!r}, "
            f"error={payload.get('error')!r}"
        )
    result = payload["data"]["result"]
    series = []
    for entry in result:
        metric = entry.get("metric", {})
        key = (metric.get("pod", ""), metric.get("container", ""))
        if metric.get("namespace"):
            key = (*key, metric["namespace"])
        values = entry.get("values") or []
        samples = np.asarray([float(v) for _, v in values], dtype=np.float64)
        # Stale markers ("NaN") / division artifacts ("+Inf") carry no usage
        # information and would poison max/percentile reductions — drop them
        # (same rule as the native parser).
        series.append((key, samples[np.isfinite(samples)]))
    return series


def _names_cap(body: bytes, series_count: int) -> int:
    """Name-buffer size: series × (2 × k8s name limit 253 + '\\t' + '\\n'),
    never more than the response itself. If an exotic label still overflows,
    the native parser returns -1 and the caller falls back to Python — never
    truncation."""
    return max(4096, min(len(body), series_count * 512))


def _split_keys(names_value: bytes, n: int) -> list[SeriesKey]:
    """Decode the native names buffer: '\\n'-joined "pod\\tcontainer" records,
    extended to "pod\\tcontainer\\tnamespace" for series carrying a namespace
    label (multi-namespace coalesced queries) — the key arity mirrors the
    record's."""
    if not n:
        return []
    return [
        tuple(record.split("\t"))
        for record in names_value.decode("utf-8", errors="replace").split("\n")[:n]
    ]


def parse_matrix_native(body: bytes) -> Optional[list[tuple[SeriesKey, np.ndarray]]]:
    """Native parse; None when the library is unavailable or reports malformed
    input (caller falls back to Python)."""
    lib = _load_library()
    if lib is None:
        return None

    # Size buffers by over-allocation rather than a krr_count_series pre-scan:
    # the count would cost a full extra pass over every response on the bulk-
    # fetch hot path, while these buffers are transient and ~body-sized. (The
    # digest path keeps the pre-scan — there counting avoids a buckets×series
    # allocation that dwarfs the body.) Caps too small ⇒ -1 ⇒ Python fallback.
    values_cap = max(len(body) // 8, 1024)  # every sample costs >8 response bytes
    series_cap = max(len(body) // 24, 64)  # a series entry costs >24 bytes
    names_cap = max(len(body), 4096)
    values = np.empty(values_cap, dtype=np.float64)
    lens = np.empty(series_cap, dtype=np.int64)
    names = ctypes.create_string_buffer(names_cap)

    n = lib.krr_parse_matrix(
        body,
        len(body),
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        values_cap,
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        series_cap,
        names,
        names_cap,
    )
    if n < 0:
        return None
    keys = _split_keys(names.value, n)
    series = []
    offset = 0
    for i in range(n):
        length = int(lens[i])
        series.append((keys[i], values[offset : offset + length].copy()))
        offset += length
    return series


def parse_matrix(body: bytes) -> list[tuple[SeriesKey, np.ndarray]]:
    """Parse a query_range matrix response: native when possible, Python otherwise."""
    # Error payloads route through the Python parser, which raises with the
    # server's error message (the native scanner only understands matrices).
    if b'"status":"error"' not in body[:4096]:
        native = parse_matrix_native(body)
        if native is not None:
            return native
    return parse_matrix_python(body)


#: Result of a fused parse+digest pass: per-series (series key, bucket counts,
#: total sample count, exact max).
DigestedSeries = list[tuple[SeriesKey, np.ndarray, float, float]]


def _digest_python(samples: np.ndarray, gamma: float, min_value: float, num_buckets: int):
    """Vectorized fallback with the bucketize semantics of `krr_tpu.ops.digest`."""
    counts = np.zeros(num_buckets, dtype=np.float64)
    if samples.size == 0:
        return counts, 0.0, -np.inf
    safe = np.maximum(samples, min_value)
    raw = np.floor(np.log(safe / min_value) / np.log(gamma)).astype(np.int64)
    idx = np.where(samples <= min_value, 0, 1 + np.clip(raw, 0, num_buckets - 2))
    np.add.at(counts, idx, 1.0)
    return counts, float(samples.size), float(samples.max())


def parse_matrix_digest(
    body: bytes, gamma: float, min_value: float, num_buckets: int
) -> DigestedSeries:
    """Fused parse + per-series digest accumulation.

    The streaming-ingest hot path: every sample goes straight from the
    response bytes into its log bucket (native single pass, O(num_buckets)
    memory per series — raw sample arrays are never materialized). Bucket
    layout matches `krr_tpu.ops.digest.bucketize`; note the native path
    computes ``log`` in float64 while the device path uses float32, so a
    sample sitting exactly on a bucket boundary may land one bucket apart —
    within the digest's stated relative error, but not bit-identical.
    """
    lib = _load_library()
    if lib is not None and b'"status":"error"' not in body[:4096]:
        # Exact series count up front: the counts matrix is
        # series x num_buckets doubles, so a body-length-proportional guess
        # would allocate ~320x the response size for nothing.
        series_cap = lib.krr_count_series(body, len(body))
        if series_cap >= 0:
            names_cap = _names_cap(body, series_cap)
            counts = np.zeros((series_cap, num_buckets), dtype=np.float64)
            totals = np.zeros(series_cap, dtype=np.float64)
            peaks = np.zeros(series_cap, dtype=np.float64)
            names = ctypes.create_string_buffer(names_cap)
            n = lib.krr_parse_matrix_digest(
                body,
                len(body),
                gamma,
                min_value,
                num_buckets,
                counts.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                totals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                peaks.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                series_cap,
                names,
                names_cap,
            )
            if n >= 0:
                keys = _split_keys(names.value, n)
                return [(keys[i], counts[i].copy(), float(totals[i]), float(peaks[i])) for i in range(n)]
    return [
        (key, *_digest_python(samples, gamma, min_value, num_buckets))
        for key, samples in parse_matrix(body)
    ]


class StreamIngest:
    """Streaming fused parse+fold over arbitrary chunk boundaries
    (`native/faststream.cpp`): feed response bytes as they arrive from the
    socket; per-series digests/stats accumulate in native memory, so neither
    the body nor raw samples are ever materialized. ``num_buckets=0`` selects
    the stats-only sink (memory resource). None from :func:`open_stream` when
    the native library is unavailable — callers fall back to buffered parsing.

    Usage::

        stream = open_stream(gamma, min_value, num_buckets)
        while chunk := read(...):
            stream.feed(chunk)
        series = stream.finish()   # DigestedSeries or SeriesStats
    """

    def __init__(self, lib, handle: int, num_buckets: int):
        self._lib = lib
        self._handle = handle
        self._num_buckets = num_buckets
        self._count: Optional[int] = None
        #: Serializes every native call against abort(): on the httpx route
        #: feed/finalize run in executor threads, and a cancelled awaiter's
        #: cleanup could otherwise free the handle WHILE a worker is still
        #: parsing into it (use-after-free). With the lock, abort blocks
        #: until the in-flight call returns; the late worker then sees the
        #: cleared handle and raises instead of touching freed memory.
        self._op_lock = threading.Lock()

    def feed(self, chunk: bytes) -> None:
        with self._op_lock:
            if self._handle is None:
                raise ValueError("stream already finished")
            if self._lib.krr_stream_feed(self._handle, chunk, len(chunk)) != 0:
                raise ValueError("malformed Prometheus stream")

    def feed_view(self, buf, n: int) -> None:
        """Feed the first ``n`` bytes of a REUSABLE writable buffer (a pooled
        ``bytearray``) without materializing a ``bytes`` copy per chunk — the
        zero-hop sink path's fast lane. The native parser consumes the bytes
        before returning (anything unconsumed is copied into its own carry),
        so the caller may refill ``buf`` as soon as this returns."""
        with self._op_lock:
            if self._handle is None:
                raise ValueError("stream already finished")
            ptr = ctypes.cast((ctypes.c_char * n).from_buffer(buf), ctypes.c_char_p)
            if self._lib.krr_stream_feed(self._handle, ptr, n) != 0:
                raise ValueError("malformed Prometheus stream")

    def finish_parse(self) -> "StreamIngest":
        """End-of-body validation WITHOUT reading anything out: the handle
        stays alive for :meth:`read_meta` / :meth:`fold_counts_into`, and the
        caller owns releasing it (:meth:`free`). This is the fleet fast path —
        the folded state crosses into Python as one band-sparse native add
        into the final arrays instead of a dense matrix readout."""
        with self._op_lock:
            handle = self._handle
            if handle is None:
                raise ValueError("stream already finished")
            n = self._lib.krr_stream_finish(handle)
            if n < 0:
                self._handle = None
                self._lib.krr_stream_free(handle)
                raise ValueError(
                    "truncated Prometheus stream (body ended mid-series)"
                    if n == -3
                    else "malformed Prometheus stream (no result array)"
                )
            self._count = int(n)
            return self

    def read_meta(self) -> tuple[bytes, np.ndarray, np.ndarray]:
        """(names bytes, totals, peaks) — the cheap per-series readout (no
        counts matrix) that lets the caller build a row mapping before the
        native counts fold. Requires :meth:`finish_parse`. The names bytes
        are '\\n'-joined "pod\\tcontainer" records (:func:`_split_keys`);
        identical bytes across windows mean an identical series list, so
        callers can reuse a cached mapping without decoding."""
        with self._op_lock:
            if self._handle is None or self._count is None:
                raise ValueError("read_meta requires a live, parse-finished stream")
            n = self._count
            totals = np.empty(n, dtype=np.float64)
            peaks = np.empty(n, dtype=np.float64)
            if not n:
                return b"", totals, peaks
            names_cap = self._lib.krr_stream_names_len(self._handle)
            names = ctypes.create_string_buffer(names_cap)
            rc = self._lib.krr_stream_read(
                self._handle,
                names,
                names_cap,
                totals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                peaks.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                None,
                n,
            )
            if rc != 0:
                raise ValueError("stream readout capacity mismatch")
            return names.raw[:names_cap], totals, peaks

    def fold_counts_into(self, rows: np.ndarray, dst: np.ndarray) -> None:
        """Add every series' touched bucket span into ``dst[rows[i]]``
        (``rows[i] < 0`` skips) — one GIL-released native pass straight into
        the caller's [n_rows × num_buckets] float64 accumulator (digest mode
        only). Requires :meth:`finish_parse`."""
        with self._op_lock:
            # Real exceptions, not asserts: these guard a raw native write —
            # stripped under ``python -O`` they would become out-of-bounds
            # memory corruption instead of a caller error.
            if self._handle is None or self._count is None:
                raise ValueError("fold_counts_into requires a live, parse-finished stream")
            if not (
                dst.dtype == np.float64
                and dst.flags["C_CONTIGUOUS"]
                and dst.ndim == 2
                and dst.shape[1] == self._num_buckets
            ):
                raise ValueError(
                    f"dst must be C-contiguous float64 [rows × {self._num_buckets}]"
                )
            rows = np.ascontiguousarray(rows, dtype=np.int64)
            if rows.shape != (self._count,):
                raise ValueError(f"rows must cover all {self._count} series")
            rc = self._lib.krr_stream_fold_into(
                self._handle,
                rows.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
                self._count,
                dst.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                dst.shape[0],
            )
            if rc != 0:
                raise ValueError("stream fold shape/mode mismatch")

    def finish(self):
        """Close the stream and return the folded series.

        Digest mode returns the MATRIX form ``(keys, counts [n × buckets]
        float64, totals [n], peaks [n])`` — the arrays are exclusively owned
        by the caller. The earlier per-row tuple readout (one ``.copy()`` +
        tuple per series) cost ~3.7 s per 100k-series window, several times
        the native parse itself; consumers fold the matrix with vectorized
        ops instead (`krr_tpu.integrations.prometheus`). Stats mode returns
        ``[(key, total, peak), …]`` — scalars, nothing to vectorize."""
        with self._op_lock:
            return self._finish_locked()

    def _finish_locked(self):
        handle, self._handle = self._handle, None
        if handle is None:
            raise ValueError("stream already finished")
        try:
            n = self._lib.krr_stream_finish(handle)
            if n < 0:
                raise ValueError(
                    "truncated Prometheus stream (body ended mid-series)"
                    if n == -3
                    else "malformed Prometheus stream (no result array)"
                )
            if n == 0:
                if self._num_buckets:
                    empty = np.zeros((0, self._num_buckets), dtype=np.float64)
                    return [], empty, np.zeros(0, np.float64), np.zeros(0, np.float64)
                return []
            names_cap = self._lib.krr_stream_names_len(handle)
            names = ctypes.create_string_buffer(names_cap)
            totals = np.zeros(n, dtype=np.float64)
            peaks = np.zeros(n, dtype=np.float64)
            counts = (
                np.zeros((n, self._num_buckets), dtype=np.float64)
                if self._num_buckets
                else None
            )
            rc = self._lib.krr_stream_read(
                handle,
                names,
                names_cap,
                totals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                peaks.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                counts.ctypes.data_as(ctypes.POINTER(ctypes.c_double)) if counts is not None else None,
                n,
            )
            if rc != 0:
                raise ValueError("stream readout capacity mismatch")
            keys = _split_keys(names.raw[:names_cap], n)
            if counts is not None:
                return keys, counts, totals, peaks
            return [(keys[i], float(totals[i]), float(peaks[i])) for i in range(n)]
        finally:
            self._lib.krr_stream_free(handle)

    def abort(self) -> None:
        """Release native memory without reading results (fetch failed).
        Blocks until any in-flight native call on another thread returns —
        never frees under a live parser (see ``_op_lock``)."""
        with self._op_lock:
            handle, self._handle = self._handle, None
            if handle is not None:
                self._lib.krr_stream_free(handle)

    #: Terminal call of the finish_parse path (same release as a failed
    #: fetch's abort — the name marks intent at call sites).
    free = abort

    def __del__(self):
        # Safety net for ownership gaps (e.g. a consumer cancelled between
        # fetch and fold): a still-live handle pins up to GB-scale native
        # state, far too big to leave to process exit. No lock: reachable
        # refcount zero means no concurrent op can hold the stream.
        handle = getattr(self, "_handle", None)
        if handle is not None:
            self._handle = None
            self._lib.krr_stream_free(handle)


def stream_available() -> bool:
    """Whether streaming ingest exists here (native library loaded)."""
    return _load_library() is not None


def open_stream(
    gamma: float, min_value: float, num_buckets: int, reserve_series: int = 0
) -> Optional[StreamIngest]:
    """A streaming ingest handle, or None when the native library (the only
    implementation) is unavailable. ``num_buckets=0`` = stats-only sink.
    ``reserve_series`` pre-sizes the native state for the expected series
    count (the probed estimate, padded for churn): no realloc-doubling
    copies, and the counts matrix's untouched pages stay lazily zero-mapped
    (a reserve failure silently falls back to growth-on-demand)."""
    lib = _load_library()
    if lib is None:
        return None
    handle = lib.krr_stream_new(gamma, min_value, num_buckets)
    if not handle:
        return None
    if reserve_series > 0:
        lib.krr_stream_reserve(handle, reserve_series + reserve_series // 8 + 64)
    return StreamIngest(lib, handle, num_buckets)


#: Result of a stats-only parse: per-series (series key, total sample count,
#: exact max).
SeriesStats = list[tuple[SeriesKey, float, float]]


def parse_matrix_stats(body: bytes) -> SeriesStats:
    """Per-series count + exact max in one native pass — the memory-resource
    ingest (max × buffer needs no histogram, and no per-sample log())."""
    lib = _load_library()
    if lib is not None and b'"status":"error"' not in body[:4096]:
        series_cap = lib.krr_count_series(body, len(body))
        if series_cap >= 0:
            names_cap = _names_cap(body, series_cap)
            totals = np.zeros(series_cap, dtype=np.float64)
            peaks = np.zeros(series_cap, dtype=np.float64)
            names = ctypes.create_string_buffer(names_cap)
            n = lib.krr_parse_matrix_stats(
                body,
                len(body),
                totals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                peaks.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                series_cap,
                names,
                names_cap,
            )
            if n >= 0:
                keys = _split_keys(names.value, n)
                return [(keys[i], float(totals[i]), float(peaks[i])) for i in range(n)]
    return [
        (key, float(samples.size), float(samples.max()) if samples.size else float("-inf"))
        for key, samples in parse_matrix(body)
    ]


# --------------------------------------------------------------- remote-write
class RemoteWriteError(ValueError):
    """Malformed remote-write body (snappy framing or protobuf bytes) — the
    listener answers 400 and counts it; nothing was partially ingested."""


class RemoteWriteTooLarge(RemoteWriteError):
    """The snappy preamble promises more than the decode cap — rejected
    before allocating (decompression-bomb guard); the listener answers 413."""


#: Decoded remote-write body: '\n'-joined per-series records of '\t'-joined
#: label name/value fields (wire order), flat series-major float64 samples,
#: parallel int64 millisecond timestamps, and per-series sample counts. The
#: native and Python decoders produce BIT-identICAL tuples — the decoder
#: parity test's contract.
DecodedWrite = tuple[bytes, np.ndarray, np.ndarray, np.ndarray]


def _snappy_decompress_python(body: bytes, max_decoded: int) -> bytes:
    """Snappy BLOCK format (the remote-write framing), pure Python: uvarint
    uncompressed-length preamble, then literal and 1/2/4-byte-offset copy
    tags. Same malformed-input rules as the native twin."""
    pos = 0
    expect = 0
    shift = 0
    while True:
        if pos >= len(body) or shift >= 64:
            raise RemoteWriteError("truncated snappy length preamble")
        b = body[pos]
        pos += 1
        expect |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    if expect > max_decoded:
        raise RemoteWriteTooLarge(
            f"snappy preamble promises {expect} bytes (cap {max_decoded})"
        )
    out = bytearray()
    n = len(body)
    while pos < n:
        tag = body[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            length = (tag >> 2) + 1
            if length > 60:
                extra = length - 60
                if pos + extra > n:
                    raise RemoteWriteError("truncated snappy literal length")
                length = int.from_bytes(body[pos : pos + extra], "little") + 1
                pos += extra
            if pos + length > n or len(out) + length > expect:
                raise RemoteWriteError("truncated snappy literal")
            out += body[pos : pos + length]
            pos += length
        else:  # copy
            if kind == 1:
                length = ((tag >> 2) & 7) + 4
                if pos >= n:
                    raise RemoteWriteError("truncated snappy copy")
                offset = ((tag >> 5) << 8) | body[pos]
                pos += 1
            elif kind == 2:
                length = (tag >> 2) + 1
                if pos + 2 > n:
                    raise RemoteWriteError("truncated snappy copy")
                offset = int.from_bytes(body[pos : pos + 2], "little")
                pos += 2
            else:
                length = (tag >> 2) + 1
                if pos + 4 > n:
                    raise RemoteWriteError("truncated snappy copy")
                offset = int.from_bytes(body[pos : pos + 4], "little")
                pos += 4
            if offset <= 0 or offset > len(out) or len(out) + length > expect:
                raise RemoteWriteError("invalid snappy copy")
            # Overlapping copies (offset < length) are the RLE idiom: the
            # defined semantics is a byte-at-a-time forward copy.
            for _ in range(length):
                out.append(out[-offset])
    if len(out) != expect:
        raise RemoteWriteError("snappy output length mismatch")
    return bytes(out)


def _pb_varint(data: bytes, pos: int) -> tuple[int, int]:
    value = 0
    shift = 0
    while shift < 64:
        if pos >= len(data):
            raise RemoteWriteError("truncated protobuf varint")
        b = data[pos]
        pos += 1
        value |= (b & 0x7F) << shift
        if not b & 0x80:
            return value, pos
        shift += 7
    raise RemoteWriteError("overlong protobuf varint")


def _pb_skip(data: bytes, pos: int, wire_type: int) -> int:
    if wire_type == 0:
        _, pos = _pb_varint(data, pos)
        return pos
    if wire_type == 1:
        pos += 8
    elif wire_type == 2:
        length, pos = _pb_varint(data, pos)
        pos += length
    elif wire_type == 5:
        pos += 4
    else:
        raise RemoteWriteError(f"unsupported protobuf wire type {wire_type}")
    if pos > len(data):
        raise RemoteWriteError("truncated protobuf field")
    return pos


def decode_remote_write_python(
    body: bytes, max_decoded: int = 64 << 20
) -> DecodedWrite:
    """Pure-Python remote-write decoder: the fallback twin of
    :func:`decode_remote_write_native`, and the oracle its parity test
    compares against. Raises :class:`RemoteWriteError` on malformed bytes."""
    data = _snappy_decompress_python(body, max_decoded)
    records: list[bytes] = []
    values: list[float] = []
    timestamps: list[int] = []
    lens: list[int] = []
    unpack_double = struct.Struct("<d").unpack_from

    pos = 0
    while pos < len(data):
        key, pos = _pb_varint(data, pos)
        field, wire_type = key >> 3, key & 7
        if field == 1 and wire_type == 2:  # repeated TimeSeries
            ts_len, pos = _pb_varint(data, pos)
            ts_end = pos + ts_len
            if ts_end > len(data):
                raise RemoteWriteError("truncated TimeSeries")
            fields: list[bytes] = []
            count = 0
            while pos < ts_end:
                sub_key, pos = _pb_varint(data, pos)
                sub_field, sub_wt = sub_key >> 3, sub_key & 7
                if sub_field in (1, 2) and sub_wt == 2:
                    sub_len, pos = _pb_varint(data, pos)
                    sub_end = pos + sub_len
                    if sub_end > ts_end:
                        raise RemoteWriteError("truncated TimeSeries submessage")
                    if sub_field == 1:  # Label{name, value}
                        name = value = b""
                        while pos < sub_end:
                            l_key, pos = _pb_varint(data, pos)
                            l_field, l_wt = l_key >> 3, l_key & 7
                            if l_field in (1, 2) and l_wt == 2:
                                l_len, pos = _pb_varint(data, pos)
                                if pos + l_len > sub_end:
                                    raise RemoteWriteError("truncated Label string")
                                chunk = data[pos : pos + l_len]
                                pos += l_len
                                if l_field == 1:
                                    name = chunk
                                else:
                                    value = chunk
                            else:
                                pos = _pb_skip(data, pos, l_wt)
                        if pos != sub_end:
                            # A skip crossed the Label boundary: the native
                            # scanner bounds every read by the submessage and
                            # rejects this — the twin must too.
                            raise RemoteWriteError("misaligned Label submessage")
                        if b"\t" in name or b"\n" in name or b"\t" in value or b"\n" in value:
                            raise RemoteWriteError("separator byte inside a label")
                        fields.append(name + b"\t" + value)
                    else:  # Sample{value, timestamp}
                        v = 0.0
                        ts = 0
                        while pos < sub_end:
                            s_key, pos = _pb_varint(data, pos)
                            s_field, s_wt = s_key >> 3, s_key & 7
                            if s_field == 1 and s_wt == 1:
                                if pos + 8 > sub_end:
                                    raise RemoteWriteError("truncated Sample value")
                                (v,) = unpack_double(data, pos)
                                pos += 8
                            elif s_field == 2 and s_wt == 0:
                                raw, pos = _pb_varint(data, pos)
                                # int64 two's complement, like the native cast
                                ts = raw - (1 << 64) if raw >= (1 << 63) else raw
                            else:
                                pos = _pb_skip(data, pos, s_wt)
                        if pos != sub_end:
                            raise RemoteWriteError("misaligned Sample submessage")
                        values.append(v)
                        timestamps.append(ts)
                        count += 1
                else:
                    pos = _pb_skip(data, pos, sub_wt)
            if pos != ts_end:
                raise RemoteWriteError("misaligned TimeSeries submessage")
            records.append(b"\t".join(fields))
            lens.append(count)
        else:  # metadata etc.: skipped
            pos = _pb_skip(data, pos, wire_type)
    return (
        b"\n".join(records),
        np.asarray(values, dtype=np.float64),
        np.asarray(timestamps, dtype=np.int64),
        np.asarray(lens, dtype=np.int64),
    )


def decode_remote_write_native(
    body: bytes, max_decoded: int = 64 << 20
) -> Optional[DecodedWrite]:
    """Native remote-write decode, or None when the library is unavailable /
    a capacity estimate fell short (callers fall back to the Python twin).
    Malformed bytes raise :class:`RemoteWriteError`, same as the fallback."""
    lib = _load_library()
    if lib is None:
        return None
    decoded_len = lib.krr_rw_uncompressed_len(body, len(body))
    if decoded_len < 0:
        raise RemoteWriteError("truncated snappy length preamble")
    if decoded_len > max_decoded:
        raise RemoteWriteTooLarge(
            f"snappy preamble promises {decoded_len} bytes (cap {max_decoded})"
        )
    # Worst-case shapes from the uncompressed size: a Sample can be 2 wire
    # bytes (empty submessage -> value 0 @ ts 0), a TimeSeries 2 bytes, and
    # the names arena adds at most one separator per >=2-byte wire string.
    values_cap = decoded_len // 2 + 16
    series_cap = decoded_len // 2 + 16
    names_cap = 2 * decoded_len + 64
    values = np.empty(values_cap, dtype=np.float64)
    timestamps = np.empty(values_cap, dtype=np.int64)
    lens = np.empty(series_cap, dtype=np.int64)
    names = ctypes.create_string_buffer(names_cap)
    out_values_n = ctypes.c_longlong(0)
    out_names_len = ctypes.c_longlong(0)
    n = lib.krr_rw_decode(
        body,
        len(body),
        max_decoded,
        names,
        names_cap,
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        timestamps.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        values_cap,
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        series_cap,
        ctypes.byref(out_values_n),
        ctypes.byref(out_names_len),
    )
    if n == -1:
        return None  # capacity shortfall: let the Python twin handle it
    if n == -3:
        raise RemoteWriteTooLarge("decoded size exceeds the cap")
    if n < 0:
        raise RemoteWriteError("malformed remote-write body")
    return (
        names.raw[: out_names_len.value],
        values[: out_values_n.value].copy(),
        timestamps[: out_values_n.value].copy(),
        lens[:n].copy(),
    )


def decode_remote_write(body: bytes, max_decoded: int = 64 << 20) -> DecodedWrite:
    """Decode one remote-write body: native scanner when available, pure
    Python otherwise — identical outputs either way."""
    decoded = decode_remote_write_native(body, max_decoded)
    if decoded is None:
        decoded = decode_remote_write_python(body, max_decoded)
    return decoded


def digest_samples(
    samples: np.ndarray, gamma: float, min_value: float, num_buckets: int
) -> tuple[np.ndarray, float, float]:
    """Digest a plain sample array through the SAME implementation the range
    fetch uses: the native bucketizer when the library is loaded, the Python
    fallback otherwise. The push ingest plane folds through this so push-fed
    windows are bit-identical to range-fetched ones in either regime (the
    two bucketize expressions can round a boundary-sitting sample into
    adjacent buckets; mixing them across paths would break the push-vs-pull
    exactness gate)."""
    lib = _load_library()
    samples = np.ascontiguousarray(samples, dtype=np.float64)
    if lib is None:
        return _digest_python(samples, gamma, min_value, num_buckets)
    counts = np.zeros(num_buckets, dtype=np.float64)
    if samples.size == 0:
        return counts, 0.0, -np.inf
    total = ctypes.c_double(0.0)
    peak = ctypes.c_double(0.0)
    rc = lib.krr_digest_array(
        samples.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        samples.size,
        gamma,
        min_value,
        num_buckets,
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.byref(total),
        ctypes.byref(peak),
    )
    if rc != 0:
        raise ValueError(f"invalid digest parameters (gamma={gamma}, min_value={min_value})")
    return counts, total.value, peak.value
