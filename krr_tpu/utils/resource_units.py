"""Kubernetes resource-quantity parsing and humanized formatting.

Behavior-compatible with the reference implementation
(`/root/reference/robusta_krr/utils/resource_units.py:4-48`):

* ``UNITS`` maps suffixes to multipliers. Parsing tries suffixes in insertion
  order and takes the first match (so ``Ki``..``Ei`` binary suffixes are tried
  before the decimal ``k``..``E`` ones, and a bare ``m`` means milli).
* Formatting optionally truncates to the first N significant digits (zeroing
  the rest, not rounding), then renders with the *largest* unit that divides
  the value evenly, scanning units from largest to smallest.

Everything is exact ``Decimal`` arithmetic — this module is part of the host
"Decimal edge" that keeps parity with the reference while the heavy reductions
run on TPU (see SURVEY.md §7 "Host edge").
"""

from __future__ import annotations

from decimal import Decimal
from typing import Optional

# Suffix → multiplier. Insertion order is load-bearing for `parse` (first
# matching suffix wins) and, reversed, for `format` (largest unit first).
UNITS: dict[str, Decimal] = {
    "m": Decimal("1e-3"),
    "Ki": Decimal(1024),
    "Mi": Decimal(1024**2),
    "Gi": Decimal(1024**3),
    "Ti": Decimal(1024**4),
    "Pi": Decimal(1024**5),
    "Ei": Decimal(1024**6),
    "k": Decimal("1e3"),
    "M": Decimal("1e6"),
    "G": Decimal("1e9"),
    "T": Decimal("1e12"),
    "P": Decimal("1e15"),
    "E": Decimal("1e18"),
}


def parse(quantity: str) -> Decimal:
    """Parse a k8s quantity string (``"100m"``, ``"128Mi"``, ``"2"``) to a Decimal."""
    for suffix, multiplier in UNITS.items():
        if quantity.endswith(suffix):
            return Decimal(quantity[: -len(suffix)]) * multiplier
    return Decimal(quantity)


def _truncate_significant(value: Decimal, digits: int) -> Decimal:
    """Keep only the first ``digits`` significant digits, zero-filling the rest.

    Truncation (not rounding), matching the reference's digit-tuple surgery:
    123456 with digits=3 → 123000.
    """
    sign, mantissa, exponent = value.as_tuple()
    kept = list(mantissa[:digits]) + [0] * (len(mantissa) - digits)
    return Decimal((sign, tuple(kept), exponent))


def format(value: Decimal, precision: Optional[int] = None) -> str:
    """Render a Decimal with the largest evenly-dividing unit suffix."""
    if precision is not None:
        assert precision >= 0
        value = _truncate_significant(value, precision)

    if value == 0:
        return "0"

    for suffix, multiplier in reversed(UNITS.items()):
        if value % multiplier == 0:
            return f"{int(value / multiplier)}{suffix}"
    return str(value)
