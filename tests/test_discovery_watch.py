"""Watch-driven incremental discovery (`--discovery-mode watch`).

The correctness bar under test: at every reconcile the watch-maintained
inventory must be BIT-IDENTICAL — same objects, same staged order — to what
a fresh relist would return, through every rung of the resync ladder:
ordinary churn, bookmark-only progress, mid-stream disconnects, forced
``410 Gone`` resyncs, a divergence injected behind the watcher's back
(caught by the verify relist), and a warm restart from the persisted
snapshot. A serve-level churn soak then pins the same discipline end to
end: watch-mode scheduler ticks publish byte-identical results (and leave a
bit-identical digest store) vs a relist-mode control through a fault
timeline.
"""

import asyncio
import json
import time

import numpy as np
import pytest
import yaml

from krr_tpu.core.config import Config
from krr_tpu.integrations.kubernetes import KubernetesLoader
from krr_tpu.obs.metrics import MetricsRegistry

from .fakes.servers import KIND_ATTRS, FakeBackend, FakeCluster, FakeMetrics, ServerThread


# ------------------------------------------------------------------ helpers
def _dump(objects):
    return [obj.model_dump() for obj in objects]


def _brief(objects):
    return [(o.kind, o.namespace, o.name, o.container, tuple(o.pods)) for o in objects]


def _cluster_keys(cluster: FakeCluster) -> set:
    return {
        (kind, item["metadata"]["namespace"], item["metadata"]["name"])
        for kind, attr in KIND_ATTRS.items()
        for item in getattr(cluster, attr)
        if item["metadata"]["namespace"] != "kube-system"
    }


def _write_kubeconfig(path, url: str) -> str:
    path.write_text(
        yaml.dump(
            {
                "current-context": "fake",
                "contexts": [{"name": "fake", "context": {"cluster": "fake", "user": "fake"}}],
                "clusters": [{"name": "fake", "cluster": {"server": url}}],
                "users": [{"name": "fake", "user": {"token": "t"}}],
            }
        )
    )
    return str(path)


@pytest.fixture()
def watch_env(tmp_path):
    """A function-scoped fake cluster (each test owns its event log) with a
    couple of workloads across namespaces."""
    cluster = FakeCluster()
    cluster.add_workload_with_pods("Deployment", "web", "apps", pod_count=2)
    cluster.add_workload_with_pods("Deployment", "worker", "apps", pod_count=1)
    cluster.add_workload_with_pods("StatefulSet", "db", "data", pod_count=2)
    cluster.add_workload_with_pods("Job", "migrate", "data", pod_count=1)
    cluster.add_workload_with_pods("DaemonSet", "kubelet-helper", "kube-system", pod_count=1)
    backend = FakeBackend(cluster, FakeMetrics())
    server = ServerThread(backend).start()
    kubeconfig = _write_kubeconfig(tmp_path / "kubeconfig", server.url)
    yield {
        "cluster": cluster,
        "backend": backend,
        "server": server,
        "kubeconfig": kubeconfig,
        "tmp_path": tmp_path,
    }
    server.stop()


def _config(env, **overrides) -> Config:
    defaults = dict(kubeconfig=env["kubeconfig"], quiet=True)
    defaults.update(overrides)
    return Config(**defaults)


async def _wait_bitexact(watch_loader, relist_loader, timeout=10.0):
    """Poll until the watch reconcile is bit-identical to a fresh relist
    (watch delivery is asynchronous); the final assert carries the diff."""
    deadline = time.time() + timeout
    while True:
        watched = await watch_loader.list_scannable_objects(["fake"])
        relisted = await relist_loader.list_scannable_objects(["fake"])
        if _dump(watched) == _dump(relisted):
            return watched, relisted
        if time.time() > deadline:
            assert _brief(watched) == _brief(relisted)
            assert _dump(watched) == _dump(relisted)
        await asyncio.sleep(0.03)


def _run(coro):
    return asyncio.run(coro)


# -------------------------------------------------------------- reconcile
class TestWatchReconcile:
    def test_cold_seed_bit_identical_to_relist(self, watch_env):
        async def main():
            watch = KubernetesLoader(_config(watch_env, discovery_mode="watch"))
            relist = KubernetesLoader(_config(watch_env))
            try:
                watched = await watch.list_scannable_objects(["fake"])
                relisted = await relist.list_scannable_objects(["fake"])
                assert _dump(watched) == _dump(relisted)
                assert len(watched) > 0
                # kube-system stays excluded, like the relist path.
                assert all(obj.namespace != "kube-system" for obj in watched)
            finally:
                await watch.close()
                await relist.close()

        _run(main())

    def test_churn_reconciles_bit_exact(self, watch_env):
        cluster = watch_env["cluster"]

        async def main():
            watch = KubernetesLoader(_config(watch_env, discovery_mode="watch"))
            relist = KubernetesLoader(_config(watch_env))
            try:
                await _wait_bitexact(watch, relist)
                # Adds, an in-place update, pod churn (add/delete/relabel),
                # and a delete+recreate (lands at the END of the relist
                # order — the insertion-order discipline under test).
                cluster.add_workload_with_pods("Deployment", "api", "apps", pod_count=2)
                workload = cluster._find_workload("Deployment", "web", "apps")
                workload["spec"]["template"]["spec"]["containers"].append(
                    {"name": "sidecar", "resources": {}}
                )
                cluster.update_workload("Deployment", "web", "apps")
                cluster.delete_pod("web-1", "apps")
                cluster.add_pod("web-9", "apps", {"app": "web"})
                cluster.update_pod("worker-0", "apps", {"app": "none"})  # unselects it
                cluster.delete_workload("StatefulSet", "db", "data")
                cluster.delete_pod("db-0", "data")
                cluster.delete_pod("db-1", "data")
                cluster.add_workload_with_pods("StatefulSet", "db", "data", pod_count=1)
                watched, _ = await _wait_bitexact(watch, relist)
                names = [(o.kind, o.name) for o in watched]
                assert ("Deployment", "api") in names
                # The watch fed the change without any additional workload
                # LIST (the pod/workload lists here all came from the relist
                # control loader + the one cold seed).
                worker = next(o for o in watched if o.name == "worker")
                assert worker.pods == []  # the relabel unselected its pod
            finally:
                await watch.close()
                await relist.close()

        _run(main())

    def test_streamed_batches_match_staged_order(self, watch_env):
        async def main():
            watch = KubernetesLoader(_config(watch_env, discovery_mode="watch"))
            try:
                staged = await watch.list_scannable_objects(["fake"])
                batches = []
                async for ordinal, positions, objects in watch.stream_scannable_objects(["fake"]):
                    batches.append((ordinal, positions, objects))
                flat = sorted(
                    (
                        (ordinal, position, obj)
                        for ordinal, positions, objects in batches
                        for position, obj in zip(positions, objects)
                    ),
                    key=lambda t: (t[0], t[1]),
                )
                assert _dump([obj for _o, _p, obj in flat]) == _dump(staged)
                # One batch per namespace, like the relist streamed path.
                assert all(
                    len({obj.namespace for obj in objects}) == 1
                    for _ordinal, _positions, objects in batches
                )
            finally:
                await watch.close()

        _run(main())


# ---------------------------------------------------------- resync ladder
class TestResyncLadder:
    def test_bookmark_progress_survives_compaction_without_relist(self, watch_env):
        cluster = watch_env["cluster"]
        backend = watch_env["backend"]

        async def main():
            registry = MetricsRegistry()
            watch = KubernetesLoader(_config(watch_env, discovery_mode="watch"), metrics=registry)
            relist = KubernetesLoader(_config(watch_env))
            try:
                await _wait_bitexact(watch, relist)
                seed_relists = registry.total("krr_tpu_discovery_relists_total")
                # Bookmark-only progress: no object churn, but every stream's
                # resourceVersion advances past the compaction floor. All 6
                # streams (4 workload kinds + the apps/data pod watches)
                # must have relayed the bookmark before it becomes the floor.
                cluster.bookmark()
                deadline = time.time() + 5.0
                def bookmarks() -> float:
                    return sum(
                        value
                        for series, value in registry.series(
                            "krr_tpu_discovery_watch_events_total"
                        ).items()
                        if ("type", "bookmark") in set(series)
                    )
                while time.time() < deadline and bookmarks() < 6:
                    await asyncio.sleep(0.02)
                assert bookmarks() >= 6
                cluster.compact_watch()
                # …so the reconnect after a disconnect needs NO relist.
                backend.disconnect_watches()
                await asyncio.sleep(0.3)
                await _wait_bitexact(watch, relist)
                assert registry.total("krr_tpu_discovery_relists_total") == seed_relists
                assert (registry.value("krr_tpu_discovery_relists_total", reason="410") or 0) == 0
                assert registry.total("krr_tpu_discovery_watch_restarts_total") >= 1
            finally:
                await watch.close()
                await relist.close()

        _run(main())

    def test_disconnect_catches_up_bit_exact(self, watch_env):
        cluster = watch_env["cluster"]
        backend = watch_env["backend"]

        async def main():
            registry = MetricsRegistry()
            watch = KubernetesLoader(_config(watch_env, discovery_mode="watch"), metrics=registry)
            relist = KubernetesLoader(_config(watch_env))
            try:
                await _wait_bitexact(watch, relist)
                backend.disconnect_watches()
                cluster.add_workload_with_pods("Deployment", "after-drop", "apps", pod_count=1)
                cluster.delete_workload("Job", "migrate", "data")
                watched, _ = await _wait_bitexact(watch, relist)
                assert any(o.name == "after-drop" for o in watched)
                assert registry.total("krr_tpu_discovery_watch_restarts_total") >= 1
                assert (registry.value("krr_tpu_discovery_relists_total", reason="410") or 0) == 0
            finally:
                await watch.close()
                await relist.close()

        _run(main())

    def test_410_gone_forces_relist_and_stays_bit_exact(self, watch_env):
        cluster = watch_env["cluster"]
        backend = watch_env["backend"]

        async def main():
            registry = MetricsRegistry()
            watch = KubernetesLoader(_config(watch_env, discovery_mode="watch"), metrics=registry)
            relist = KubernetesLoader(_config(watch_env))
            try:
                await _wait_bitexact(watch, relist)
                # Pause delivery, then mutate + compact past the watchers'
                # resourceVersions and disconnect: the reconnect finds its
                # history compacted (410) and must relist. The pause makes
                # the sequence race-free — no stream can consume the new
                # events before the compaction floor moves past them.
                backend.pause_watch_events = True
                cluster.add_workload_with_pods("Deployment", "survivor", "apps", pod_count=1)
                cluster.compact_watch()
                backend.disconnect_watches()
                backend.pause_watch_events = False
                watched, _ = await _wait_bitexact(watch, relist)
                assert any(o.name == "survivor" for o in watched)
                assert (registry.value("krr_tpu_discovery_relists_total", reason="410") or 0) >= 1
            finally:
                await watch.close()
                await relist.close()

        _run(main())

    def test_verify_relist_catches_divergence_behind_the_watcher(self, watch_env):
        cluster = watch_env["cluster"]

        async def main():
            registry = MetricsRegistry()
            watch = KubernetesLoader(
                _config(
                    watch_env,
                    discovery_mode="watch",
                    discovery_verify_interval_seconds=1.0,
                ),
                metrics=registry,
            )
            relist = KubernetesLoader(_config(watch_env))
            try:
                await _wait_bitexact(watch, relist)
                # Divergence injected BEHIND the watch stream: a direct list
                # append records no event, so the watcher cannot see it…
                from .fakes.servers import make_workload

                cluster.deployments.append(make_workload("Deployment", "ghost", "apps"))
                watched = await watch.list_scannable_objects(["fake"])
                assert all(o.name != "ghost" for o in watched)  # invisible to the watch
                # …until the verify relist audits ground truth.
                await asyncio.sleep(1.1)
                watched, _ = await _wait_bitexact(watch, relist)
                assert any(o.name == "ghost" for o in watched)
                assert registry.total("krr_tpu_discovery_verify_divergences_total") >= 1
                assert (registry.value("krr_tpu_discovery_relists_total", reason="verify") or 0) >= 1
            finally:
                await watch.close()
                await relist.close()

        _run(main())

    def test_warm_restart_from_snapshot_skips_cold_relist(self, watch_env):
        cluster = watch_env["cluster"]
        backend = watch_env["backend"]
        snapshot_path = str(watch_env["tmp_path"] / "discovery-inventory.json")

        async def first():
            watch = KubernetesLoader(
                _config(watch_env, discovery_mode="watch", discovery_snapshot_path=snapshot_path)
            )
            relist = KubernetesLoader(_config(watch_env))
            try:
                watched, _ = await _wait_bitexact(watch, relist)
                return _dump(watched)
            finally:
                await watch.close()  # persists the final snapshot
                await relist.close()

        expected = _run(first())
        payload = json.loads(open(snapshot_path).read())
        assert payload["v"] == 1 and payload["clusters"]

        lists_before = backend.list_request_count

        async def second():
            watch = KubernetesLoader(
                _config(watch_env, discovery_mode="watch", discovery_snapshot_path=snapshot_path)
            )
            try:
                watched = await watch.list_scannable_objects(["fake"])
                assert _dump(watched) == expected
                # The warm start issued NO workload LIST requests — the
                # snapshot seeded the inventory and the watches resumed from
                # the persisted resourceVersions.
                assert backend.list_request_count == lists_before
                # …and the watches are LIVE: post-restart churn still lands.
                cluster.add_workload_with_pods("Deployment", "post-restart", "apps", pod_count=1)
                deadline = time.time() + 5.0
                while time.time() < deadline:
                    watched = await watch.list_scannable_objects(["fake"])
                    if any(o.name == "post-restart" for o in watched):
                        break
                    await asyncio.sleep(0.03)
                assert any(o.name == "post-restart" for o in watched)
            finally:
                await watch.close()

        pods_before = backend.pod_request_count
        _run(second())
        assert backend.pod_request_count == pods_before  # no pod relists either

    def test_stale_snapshot_rides_the_410_rung(self, watch_env):
        """A snapshot whose resourceVersions predate a watch-cache
        compaction still warm-starts — the 410 answers trigger per-stream
        relists that converge back to ground truth."""
        cluster = watch_env["cluster"]
        snapshot_path = str(watch_env["tmp_path"] / "discovery-inventory.json")

        async def first():
            watch = KubernetesLoader(
                _config(watch_env, discovery_mode="watch", discovery_snapshot_path=snapshot_path)
            )
            try:
                await watch.list_scannable_objects(["fake"])
            finally:
                await watch.close()

        _run(first())
        # Invalidate the snapshot's resourceVersions: churn + compact.
        cluster.add_workload_with_pods("Deployment", "newer", "apps", pod_count=1)
        cluster.compact_watch()

        async def second():
            registry = MetricsRegistry()
            watch = KubernetesLoader(
                _config(watch_env, discovery_mode="watch", discovery_snapshot_path=snapshot_path),
                metrics=registry,
            )
            relist = KubernetesLoader(_config(watch_env))
            try:
                watched, _ = await _wait_bitexact(watch, relist)
                assert any(o.name == "newer" for o in watched)
                assert (registry.value("krr_tpu_discovery_relists_total", reason="410") or 0) >= 1
            finally:
                await watch.close()
                await relist.close()

        _run(second())


# ------------------------------------------------- pooled relist satellites
class TestPooledRelist:
    def test_pooled_loader_sees_churn_across_rounds(self, watch_env):
        """Relist mode pools the ClusterLoader (and its HTTP client) across
        rounds; the per-round begin_round() invalidation keeps pod indexes
        fresh, so churn between rounds is fully visible."""
        cluster = watch_env["cluster"]

        async def main():
            loader = KubernetesLoader(_config(watch_env))
            first = await loader.list_scannable_objects(["fake"])
            pods_first = watch_env["backend"].pod_request_count
            cluster.add_workload_with_pods("Deployment", "round2", "apps", pod_count=1)
            cluster.delete_pod("web-0", "apps")
            second = await loader.list_scannable_objects(["fake"])
            pods_second = watch_env["backend"].pod_request_count
            await loader.close()
            return first, second, pods_first, pods_second

        first, second, pods_first, pods_second = _run(main())
        assert any(o.name == "round2" for o in second)
        assert all(o.name != "round2" for o in first)
        web = next(o for o in second if o.name == "web")
        assert "web-0" not in web.pods  # the pod index really refreshed
        assert pods_second > pods_first  # per-round invalidation refetched

    def test_failed_pod_fetch_is_not_cached(self, watch_env):
        """Satellite: a pod list that raises must evict its cached future —
        a retry within the same round succeeds instead of replaying the
        cached exception."""
        backend = watch_env["backend"]

        async def main():
            from krr_tpu.integrations.kubernetes import ClusterLoader

            loader = ClusterLoader(cluster="fake", config=_config(watch_env))
            try:
                backend.fail_pod_lists = 1
                with pytest.raises(Exception):
                    await loader._namespace_pod_labels("apps")
                index = await loader._namespace_pod_labels("apps")  # retry: fresh fetch
                assert index.select({"matchLabels": {"app": "web"}})
                backend.fail_pod_lists = 1
                with pytest.raises(Exception):
                    await loader._list_pods("data", "app=db")
                assert await loader._list_pods("data", "app=db") == ["db-0", "db-1"]
            finally:
                await loader.close()

        _run(main())


# ------------------------------------------------------- serve churn soak
def _build_soak_fleet():
    """Deterministic two-namespace fleet + pre-registered series for the
    workloads the churn script later adds — so the watch run and the relist
    control share byte-identical ground truth."""
    from .fakes.chaos import ArchetypeSpec, build_fleet

    fleet = build_fleet(
        (
            ArchetypeSpec("diurnal", workloads=2, pods=1),
            ArchetypeSpec("oom-loop", workloads=2, pods=1),
        ),
        samples=240,
        seed=31,
    )
    rng = np.random.default_rng(77)
    fleet.metrics.set_series(
        "diurnal", "main", "late-0", cpu=rng.gamma(2.0, 0.1, 240), memory=rng.uniform(1e8, 2e8, 240)
    )
    return fleet


async def _wait_soak_inventory(server, cluster, timeout=8.0):
    inventory = server.session.get_inventory()
    expected = _cluster_keys(cluster)
    deadline = time.time() + timeout
    while True:
        objects = await inventory.list_scannable_objects(["fake"])
        if {(o.kind, o.namespace, o.name) for o in objects} == expected:
            return
        if time.time() > deadline:
            raise AssertionError(
                f"inventory never converged: have "
                f"{ {(o.kind, o.namespace, o.name) for o in objects} }, want {expected}"
            )
        await asyncio.sleep(0.03)


def _run_churn_soak(mode: str, tmp_path, ticks: int = 7):
    """One serve soak (real KrrServer, pinned clock) through a scripted
    churn + fault timeline; returns (report, published body bytes)."""
    from .fakes.chaos import FaultSpec, FaultTimeline, run_soak, write_kubeconfig

    fleet = _build_soak_fleet()
    server = ServerThread(fleet.backend).start()
    try:
        kubeconfig = write_kubeconfig(str(tmp_path / f"kubeconfig-{mode}"), server.url)
        config = Config(
            kubeconfig=kubeconfig,
            prometheus_url=server.url,
            strategy="tdigest",
            quiet=True,
            server_port=0,
            scan_interval_seconds=300.0,
            # The relist control re-discovers every tick, so both modes see
            # churn at identical tick boundaries.
            discovery_interval_seconds=0.001,
            # …but the verify audit stays OUT of the soak: every event must
            # ride the watch stream, not a 4ms auto-verify cadence.
            discovery_verify_interval_seconds=3600.0,
            discovery_mode=mode,
            hysteresis_enabled=False,
            prometheus_retry_deadline_seconds=1.0,
            prometheus_backoff_cap_seconds=0.2,
            other_args={"history_duration": 1, "timeframe_duration": 1},
        )
        timeline = FaultTimeline([(4, 4, FaultSpec(fail_namespaces=frozenset({"oom-loop"})))])
        cluster = fleet.cluster
        backend = fleet.backend

        async def on_tick(server_obj, sample):
            if sample.tick == 1:
                # Churn: a new workload appears (backfill leg next tick)…
                cluster.add_workload("Deployment", "late", "diurnal")
                cluster.add_pod("late-0", "diurnal", {"app": "late"})
            elif sample.tick == 2:
                # …and one disappears (watch delete → store drop op).
                cluster.delete_workload("Deployment", "diurnal-1", "diurnal")
                cluster.delete_pod("diurnal-1-0", "diurnal")
            elif sample.tick == 3 and mode == "watch":
                # Mid-soak disconnect: reconnect + catch-up, no relist.
                backend.disconnect_watches()
            await _wait_soak_inventory(server_obj, cluster)

        report = asyncio.run(
            run_soak(
                config, backend, timeline, ticks=ticks, tick_seconds=300.0, on_tick=on_tick
            )
        )
        snapshot = report.state.peek()
        return report, (snapshot.body_json if snapshot is not None else b"")
    finally:
        server.stop()


def test_watch_mode_soak_bit_exact_vs_relist_control(tmp_path):
    from .fakes.chaos import stores_bitexact

    watch_report, watch_body = _run_churn_soak("watch", tmp_path)
    relist_report, relist_body = _run_churn_soak("relist", tmp_path)

    equal, detail = stores_bitexact(watch_report.store, relist_report.store)
    assert equal, f"watch-mode store diverged from the relist control: {detail}"
    assert watch_body == relist_body, "published bytes diverged"
    assert watch_body  # something actually published

    counts = watch_report.counts()
    assert counts["scanned"] >= 6
    assert counts["degraded"] >= 1  # the fault tick quarantined, not aborted
    # The discovery posture surfaced on the read side.
    assert watch_report.state.discovery.get("mode") == "watch"
    assert relist_report.state.discovery.get("mode") == "relist"
    metrics = watch_report.metrics
    assert (metrics.value("krr_tpu_discovery_relists_total", reason="seed") or 0) >= 1
    # Every churn step rode the watch stream (no verify relist fired).
    assert metrics.total("krr_tpu_discovery_watch_events_total") >= 4
    assert (metrics.value("krr_tpu_discovery_relists_total", reason="verify") or 0) == 0
    assert metrics.total("krr_tpu_discovery_watch_restarts_total") >= 1  # the disconnect
    # Churn compaction ran off the watch deletes (the dropped workload's
    # rows left the store) — and the store ends at the control's row count.
    assert (metrics.total("krr_tpu_store_compacted_rows_total") or 0) >= 1


def test_serve_derives_snapshot_path_and_warm_restarts(tmp_path):
    """The serve composition derives ``discovery-inventory.json`` inside the
    sharded state directory; a second serve over the same state dir
    warm-starts the inventory with zero workload LIST requests."""
    from .fakes.chaos import ORIGIN, run_soak, write_kubeconfig

    fleet = _build_soak_fleet()
    server = ServerThread(fleet.backend).start()
    try:
        kubeconfig = write_kubeconfig(str(tmp_path / "kubeconfig"), server.url)
        state_path = str(tmp_path / "state")

        def config() -> Config:
            return Config(
                kubeconfig=kubeconfig,
                prometheus_url=server.url,
                strategy="tdigest",
                quiet=True,
                server_port=0,
                scan_interval_seconds=300.0,
                discovery_interval_seconds=0.05,  # snapshot save rate limit
                discovery_verify_interval_seconds=3600.0,
                discovery_mode="watch",
                hysteresis_enabled=False,
                other_args={
                    "history_duration": 1,
                    "timeframe_duration": 1,
                    "state_path": state_path,
                },
            )

        asyncio.run(run_soak(config(), fleet.backend, None, ticks=2, tick_seconds=300.0))
        snapshot_file = tmp_path / "state" / "discovery-inventory.json"
        assert snapshot_file.exists(), "serve did not derive the snapshot path"
        payload = json.loads(snapshot_file.read_text())
        assert payload["v"] == 1 and payload["clusters"]

        lists_before = fleet.backend.list_request_count
        # A later pinned start: the restarted server's windows are past the
        # persisted cursor, so its ticks actually scan (and reconcile).
        report = asyncio.run(
            run_soak(
                config(), fleet.backend, None, ticks=2, tick_seconds=300.0,
                start=ORIGIN + 3600.0 + 600.0,
            )
        )
        assert fleet.backend.list_request_count == lists_before  # warm start
        assert report.state.discovery.get("mode") == "watch"
        assert (
            report.metrics.value("krr_tpu_discovery_relists_total", reason="seed") or 0
        ) == 0
    finally:
        server.stop()


def test_watch_mode_soak_timeline_carries_discovery_block(tmp_path):
    watch_report, _body = _run_churn_soak("watch", tmp_path, ticks=4)
    records = watch_report.state.timeline.records()
    assert records, "no timeline records"
    blocks = [r.get("discovery") for r in records if r.get("discovery")]
    assert blocks, "timeline records carry no discovery block"
    assert all(b["mode"] == "watch" for b in blocks)
    assert any(b.get("adds", 0) > 0 for b in blocks)  # the churn tick's delta
    assert all("inventory_age_seconds" in b for b in blocks)
