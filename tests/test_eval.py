"""The quality scoreboard + what-if replay engine (`krr_tpu.eval`).

The acceptance contract of the eval subsystem, asserted against the chaos
archetypes' DECLARED incident labels (not re-derived ones):

* the labeled oracle — scoring an oom-loop fleet against a recommendation
  pinned between the incident peaks and the baseline reproduces exactly the
  windows the archetype generator declared;
* the ranking contract — an undersized probe strategy scores >0 OOM
  incidents on the oom-loop archetype, an oversized one scores 0 with more
  over-provisioned GB-hours, and the board ranks the safe one first;
* determinism — replaying the same inputs twice renders a byte-identical
  scoreboard (json), including through a real registered strategy;
* read-only journal evals — `krr-tpu eval` against a journal a live server
  owns takes no lock, never mutates the file, and leaves the writer
  appendable (the diff open path, satellite of PR 3).
"""

import json
import os

import numpy as np
import pytest

from krr_tpu.eval import (
    ReplayInput,
    StaticReplayStrategy,
    build_scoreboard,
    render_scoreboard,
    replay,
    score_grids,
    score_replay,
)
from krr_tpu.history.journal import RecommendationJournal

from .fakes.chaos import ORIGIN, STEP, ArchetypeSpec, build_fleet, fleet_replay_input

# Undersized / oversized static probes for the oom-loop archetype: its
# declared incident peaks sit at ~7.4e8–8.5e8 bytes, its baseline under
# ~7e8 — so 3e8 is under every peak and 5e9 is over everything.
UNDER = dict(cpu_cores=0.01, mem_bytes=3e8)
OVER = dict(cpu_cores=10.0, mem_bytes=5e9)


def oom_fleet(workloads: int = 2, samples: int = 120, seed: int = 0):
    fleet = build_fleet(
        [ArchetypeSpec("oom-loop", workloads=workloads, pods=1)],
        samples=samples,
        seed=seed,
    )
    return fleet, fleet_replay_input(fleet)


class TestLabeledOracle:
    def test_score_reproduces_declared_incident_windows(self):
        # One recommendation pinned between the oom-loop baseline (≤ ~6.9e8
        # at the pre-window ramp sample) and the declared incident peaks:
        # every declared window produces exactly one rising edge, nothing
        # else does — the score IS the label count.
        fleet, inputs = oom_fleet(workloads=1)
        windows = fleet.incident_windows("oom-loop")
        assert len(windows) == 1
        (declared,) = windows.values()
        assert declared, "oom-loop must declare incident windows"
        scores = score_grids(
            inputs.cpu,
            inputs.mem,
            rec_cpu=np.full((1, 1), 10.0),
            rec_mem=np.full((1, 1), 7.45e8),
            tick_indices=np.array([0]),
            step_seconds=inputs.step_seconds,
        )
        assert scores["oom_incidents"] == len(declared)
        assert scores["throttle_incidents"] == 0
        assert scores["samples_scored"] == inputs.cpu.shape[1]

    def test_declared_windows_bound_the_hot_samples(self):
        # The labels are authoritative: every sample above the probe line
        # falls inside a declared window.
        fleet, inputs = oom_fleet(workloads=1)
        (declared,) = fleet.incident_windows("oom-loop").values()
        hot = np.flatnonzero(inputs.mem[0] > 7.45e8)
        for i in hot:
            assert any(start <= i < end for start, end in declared), (
                f"sample {i} exceeds the probe but no declared window covers it"
            )

    def test_sustained_breach_is_one_incident(self):
        usage = np.zeros((1, 10))
        usage[0, 3:7] = 5.0  # one 4-sample plateau above the recommendation
        scores = score_grids(
            usage,
            usage,
            rec_cpu=np.full((1, 1), 1.0),
            rec_mem=np.full((1, 1), 1.0),
            tick_indices=np.array([0]),
            step_seconds=60.0,
        )
        assert scores["throttle_incidents"] == 1
        assert scores["oom_incidents"] == 1


class TestRankingContract:
    def test_undersized_scores_incidents_oversized_scores_slack(self):
        _fleet, inputs = oom_fleet()
        rows = [
            score_replay(inputs, replay(inputs, StaticReplayStrategy(**UNDER), name="under")),
            score_replay(inputs, replay(inputs, StaticReplayStrategy(**OVER), name="over")),
        ]
        under, over = rows
        assert under["oom_incidents"] > 0
        assert under["throttle_incidents"] > 0
        assert over["oom_incidents"] == 0
        assert over["throttle_incidents"] == 0
        assert over["overprovisioned_gb_hours"] > under["overprovisioned_gb_hours"]
        assert over["overprovisioned_core_hours"] > under["overprovisioned_core_hours"]

        board = build_scoreboard(
            rows,
            samples=len(inputs.timestamps),
            window_seconds=float(inputs.timestamps[-1] - inputs.timestamps[0]),
        )
        # Safety ranks above cost: the incident-free probe leads the board.
        assert [s.strategy for s in board.scores] == ["over", "under"]
        assert board.scores[0].severity.name == "GOOD"
        assert board.scores[1].severity.name == "CRITICAL"

    def test_registered_strategy_replays_through_the_gate(self):
        from krr_tpu.strategies.base import BaseStrategy

        _fleet, inputs = oom_fleet(workloads=1)
        simple = BaseStrategy.find("simple")
        strategy = simple(simple.get_settings_type()())
        replayed = replay(inputs, strategy, name="simple", ticks=6)
        row = score_replay(inputs, replayed)
        assert row["ticks"] == len(replayed.tick_indices)
        assert np.all(np.isfinite(replayed.rec_mem[:, -1]))
        # A percentile strategy over a spiky series must sit above baseline.
        assert float(replayed.rec_mem[0, -1]) > 1e8


class TestDeterminism:
    def test_replay_twice_renders_byte_identical_scoreboard(self):
        from krr_tpu.strategies.base import BaseStrategy

        _fleet, inputs = oom_fleet()
        simple = BaseStrategy.find("simple")

        def board_json() -> str:
            rows = []
            for name, strategy in (
                ("under", StaticReplayStrategy(**UNDER)),
                ("over", StaticReplayStrategy(**OVER)),
                ("simple", simple(simple.get_settings_type()())),
            ):
                rows.append(
                    score_replay(inputs, replay(inputs, strategy, name=name, ticks=8))
                )
            board = build_scoreboard(
                rows,
                samples=len(inputs.timestamps),
                window_seconds=float(inputs.timestamps[-1] - inputs.timestamps[0]),
            )
            return render_scoreboard(board, "json")

        first, second = board_json(), board_json()
        assert first == second  # byte-identical, not merely approx-equal

    def test_npz_round_trip_preserves_the_grid(self, tmp_path):
        _fleet, inputs = oom_fleet(workloads=1)
        path = str(tmp_path / "usage.npz")
        inputs.save_npz(path)
        loaded = ReplayInput.load_npz(path)
        assert loaded.keys == inputs.keys
        np.testing.assert_array_equal(loaded.cpu, inputs.cpu)
        np.testing.assert_array_equal(loaded.mem, inputs.mem)
        np.testing.assert_array_equal(loaded.timestamps, inputs.timestamps)


class TestReadonlyJournalEval:
    def _populated_journal(self, tmp_path) -> "tuple[str, RecommendationJournal]":
        path = str(tmp_path / "server.journal")
        journal = RecommendationJournal(path)
        keys = ["/default/web/app/Deployment", "/default/db/pg/StatefulSet"]
        for i in range(5):
            journal.append_tick(
                ORIGIN + STEP * i,
                keys,
                np.array([0.5 + 0.01 * i, 1.0]),
                np.array([100.0 + 5.0 * i, 800.0]),
                np.array([i == 0, i == 0]),
            )
        return path, journal

    def test_eval_does_not_perturb_a_live_writers_journal(self, tmp_path):
        # The writer stays OPEN (a running server owns this journal) while
        # the eval side builds its ReplayInput: no lock file appears, the
        # bytes on disk don't change, and the writer can keep appending.
        path, writer = self._populated_journal(tmp_path)
        with open(path, "rb") as fh:
            before = fh.read()

        inputs = ReplayInput.from_journal(path)
        assert len(inputs.keys) == 2
        assert len(inputs.timestamps) == 5
        assert not os.path.exists(path + ".lock"), "readonly open must not lock"
        with open(path, "rb") as fh:
            assert fh.read() == before, "readonly open must not rewrite the journal"

        writer.append_tick(
            ORIGIN + STEP * 5,
            ["/default/web/app/Deployment"],
            np.array([0.6]),
            np.array([130.0]),
            np.array([True]),
        )
        assert len(ReplayInput.from_journal(path).timestamps) == 6

    def test_journal_grid_is_raw_mb_scaled_to_bytes(self, tmp_path):
        path, _writer = self._populated_journal(tmp_path)
        inputs = ReplayInput.from_journal(path)
        db = inputs.keys.index("/default/db/pg/StatefulSet")
        np.testing.assert_allclose(inputs.mem[db], 800.0 * 1e6)

    def test_missing_journal_raises_value_error(self, tmp_path):
        with pytest.raises(ValueError, match="no journal"):
            ReplayInput.from_journal(str(tmp_path / "absent.journal"))


class TestEvalCli:
    def test_eval_subcommand_scores_a_live_journal(self, tmp_path):
        from click.testing import CliRunner

        from krr_tpu import main as cli_main

        cli_main.load_commands()
        path = str(tmp_path / "server.journal")
        journal = RecommendationJournal(path)
        keys = ["/default/web/app/Deployment"]
        for i in range(6):
            journal.append_tick(
                ORIGIN + STEP * i,
                keys,
                np.array([0.5]),
                np.array([100.0 + 50.0 * (i % 2)]),
                np.array([True]),
            )
        # The writer stays open across the whole CLI run.
        result = CliRunner().invoke(
            cli_main.app,
            ["eval", "--journal", path, "--strategy", "simple", "--replay-ticks", "3", "-f", "json", "-q"],
        )
        assert result.exit_code == 0, result.output
        payload = json.loads(result.output)
        assert [s["strategy"] for s in payload["scores"]] == ["simple"]
        assert payload["workloads"] == 1
        assert not os.path.exists(path + ".lock")
        journal.append_tick(  # writer survived the eval
            ORIGIN + STEP * 6, keys, np.array([0.5]), np.array([100.0]), np.array([True])
        )

    def test_eval_scoping_filters_namespaces(self, tmp_path):
        from click.testing import CliRunner

        from krr_tpu import main as cli_main

        cli_main.load_commands()
        fleet, inputs = oom_fleet(workloads=2)
        npz = str(tmp_path / "usage.npz")
        inputs.save_npz(npz)
        ns = inputs.keys[0].split("/")[1]
        result = CliRunner().invoke(
            cli_main.app,
            ["eval", "--usage", npz, "--strategy", "simple", "-n", ns, "-f", "json", "-q"],
        )
        assert result.exit_code == 0, result.output
        assert json.loads(result.output)["workloads"] == 2  # same namespace

        result = CliRunner().invoke(
            cli_main.app,
            ["eval", "--usage", npz, "-n", "no-such-namespace", "-f", "json", "-q"],
        )
        assert result.exit_code != 0
        assert "no workloads" in result.output
