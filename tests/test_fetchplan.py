"""Adaptive fetch engine tests (`krr_tpu.core.fetchplan` + the prometheus
loader's plan/pump/pool wiring):

* FetchPlanner — coalesce/shard/single decisions, telemetry EWMA, persisted
  snapshot round-trip, and the partition invariant (every object in exactly
  one group);
* AdaptiveLimiter — AIMD semantics: additive increase on queued healthy
  completions, cooldown-limited halving on degraded TTFB / failed ladders,
  plain-semaphore behavior when disabled;
* _SinkPump — the zero-hop sink path: ordered feeding on both lanes (raw
  pooled-buffer readinto, httpx bytes), error capture that keeps draining
  (the reader must never deadlock on a full queue), close/abort lifecycle;
* _RawTransport pooling — keep-alive reuse, the retry-once contract on a
  server-closed idle connection, pool width under concurrent fan-out, and
  the connection-churn counters;
* bit-exactness — adaptive-plan scans (coalesced + sharded) must produce
  BIT-identical results to the ``--fetch-plan fixed`` escape hatch across
  gather_fleet, gather_fleet_digests, a cold end-to-end Runner scan, clean
  incremental serve ticks, and quarantine catch-up legs.
"""

import asyncio
import re
import socket
import threading
import time

import numpy as np
import pytest
import yaml

from krr_tpu.core.config import Config
from krr_tpu.core.fetchplan import AdaptiveLimiter, FetchPlanner, PlanGroup
from krr_tpu.integrations.kubernetes import KubernetesLoader
from krr_tpu.integrations.prometheus import (
    BreakerOpenError,
    PrometheusLoader,
    PrometheusQueryError,
    _QueryMeter,
    _RawTransport,
    _SinkPump,
    cpu_namespace_shard_query,
)
from krr_tpu.models import ResourceType
from krr_tpu.obs.metrics import MetricsRegistry

from .fakes.servers import FakeBackend, FakeCluster, FakeMetrics, ServerThread
from .test_transport_phases import PhaseFakePrometheus


# ------------------------------------------------------------------ planner
def plan_of(planner: FetchPlanner, sizes: "dict[str, list[int]]"):
    """Build (by_namespace, pods_per_object) from {ns: [pods per object]}
    and return the plan."""
    by_namespace: dict = {}
    pods: list = []
    for ns in sizes:
        for n in sizes[ns]:
            by_namespace.setdefault(ns, []).append(len(pods))
            pods.append(n)
    return planner.plan(by_namespace, pods), by_namespace


def plan_of_auto(planner: FetchPlanner, sizes: "dict[str, list[int]]", auto_target):
    """`plan_of` with an explicit budget-derived auto target."""
    by_namespace: dict = {}
    pods: list = []
    for ns in sizes:
        for n in sizes[ns]:
            by_namespace.setdefault(ns, []).append(len(pods))
            pods.append(n)
    return planner.plan(by_namespace, pods, auto_target=auto_target), by_namespace


def assert_partition(plan: "list[PlanGroup]", by_namespace: dict) -> None:
    """Every object index appears in exactly one group."""
    all_indices = sorted(i for group in plan for i in group.indices)
    expected = sorted(i for indices in by_namespace.values() for i in indices)
    assert all_indices == expected


class TestFetchPlanner:
    def test_disabled_is_one_single_group_per_namespace(self):
        planner = FetchPlanner(enabled=False, target_series=4)
        plan, by_ns = plan_of(planner, {"b": [1, 1], "a": [100]})
        assert [g.kind for g in plan] == ["single", "single"]
        assert [g.namespaces for g in plan] == [("a",), ("b",)]
        assert_partition(plan, by_ns)

    def test_small_namespaces_coalesce_giant_ones_shard(self):
        planner = FetchPlanner(target_series=6, max_shards=16)
        plan, by_ns = plan_of(
            planner,
            {"big": [4, 4, 4], "s1": [1], "s2": [1], "s3": [1], "mid": [5]},
        )
        kinds = {g.kind for g in plan}
        assert kinds == {"sharded", "coalesced", "single"}
        shards = [g for g in plan if g.kind == "sharded"]
        assert all(g.namespaces == ("big",) for g in shards)
        assert len(shards) == 2  # ceil(12 / 6)
        assert [g.shard for g in shards] == [(0, 2), (1, 2)]
        coalesced = [g for g in plan if g.kind == "coalesced"]
        assert len(coalesced) == 1
        assert coalesced[0].namespaces == ("s1", "s2", "s3")
        singles = [g for g in plan if g.kind == "single"]
        assert [g.namespaces for g in singles] == [("mid",)]
        assert_partition(plan, by_ns)

    def test_sharding_respects_max_shards_and_workload_granularity(self):
        planner = FetchPlanner(target_series=2, max_shards=3)
        plan, by_ns = plan_of(planner, {"huge": [10] * 8})
        shards = [g for g in plan if g.kind == "sharded"]
        assert len(shards) == 3  # capped, not ceil(80/2)
        assert_partition(plan, by_ns)
        # One-workload namespaces can never shard (a workload's batched
        # query is the atomic unit).
        plan2, by2 = plan_of(FetchPlanner(target_series=2), {"mono": [1000]})
        assert [g.kind for g in plan2] == ["single"]
        assert_partition(plan2, by2)

    def test_plan_is_deterministic(self):
        sizes = {"big": [4, 4, 4], "s1": [1], "s2": [1], "z": [3]}
        p1, _ = plan_of(FetchPlanner(target_series=6), sizes)
        p2, _ = plan_of(FetchPlanner(target_series=6), sizes)
        assert p1 == p2

    def test_telemetry_raises_estimates_and_round_trips(self):
        planner = FetchPlanner(target_series=6)
        # Routed count says 2 pods, but the previous scan OBSERVED 40
        # series (unscanned pods the query still returns): the namespace
        # must stop coalescing.
        planner.observe("deceptive", series=40.0)
        plan, by_ns = plan_of(planner, {"deceptive": [1, 1], "tiny": [1]})
        kinds = {g.namespaces: g.kind for g in plan}
        assert kinds[("deceptive",)] == "sharded" or ("deceptive",) in [
            g.namespaces for g in plan if g.kind == "sharded"
        ]
        # EWMA: a second observation halves toward the new value.
        planner.observe("deceptive", series=10.0)
        assert planner.telemetry["deceptive"]["series"] == pytest.approx(25.0)
        # Snapshot → fresh planner → same estimates.
        seeded = FetchPlanner(target_series=6)
        seeded.seed(planner.state())
        assert seeded.telemetry["deceptive"]["series"] == pytest.approx(25.0)
        # Garbage seeds are ignored, not fatal.
        seeded.seed(None)
        seeded.seed({"namespaces": {"x": "not-a-dict", "y": {"series": "NaNish"}}})

    def test_auto_target_sizes_shards_to_the_sample_budget(self):
        """target_series=0 (auto): the caller's budget-derived target sizes
        the plan — a namespace needing N sub-windows under the fixed shape
        shards into ~N whole-range queries, never more."""
        planner = FetchPlanner()  # target_series defaults to 0 = auto
        # auto_target 25 series/query; 100 expected series = "4 windows"
        # under the fixed shape -> 4 shards.
        plan, by_ns = plan_of_auto(planner, {"giant": [10] * 10}, auto_target=25.0)
        shards = [g for g in plan if g.kind == "sharded"]
        assert len(shards) == 4
        assert_partition(plan, by_ns)
        # Below 2x the auto target: single, exactly the fixed shape.
        plan2, _ = plan_of_auto(planner, {"giant": [10] * 10}, auto_target=60.0)
        assert [g.kind for g in plan2] == ["single"]
        # No auto target supplied (points unknown): the static fallback.
        plan3, _ = plan_of_auto(planner, {"giant": [10] * 10}, auto_target=None)
        assert [g.kind for g in plan3] == ["single"]
        assert FetchPlanner.DEFAULT_TARGET_SERIES == 4096
        # An explicit knob beats auto.
        pinned = FetchPlanner(target_series=10)
        plan4, _ = plan_of_auto(pinned, {"giant": [10] * 10}, auto_target=1000.0)
        assert {g.kind for g in plan4} == {"sharded"}

    def test_fat_series_tighten_the_coalescing_target(self):
        planner = FetchPlanner(target_series=1000, target_bytes=1e6)
        # 1 MB per series: the effective target collapses to ~1 series, so
        # nothing coalesces even though counts alone would allow it.
        for ns in ("a", "b"):
            planner.observe(ns, series=10.0, bytes_seen=10e6)
        plan, by_ns = plan_of(planner, {"a": [10], "b": [10]})
        assert all(g.kind == "single" for g in plan)
        assert_partition(plan, by_ns)

    def test_forbid_shard_pins_single_and_round_trips(self):
        planner = FetchPlanner(target_series=6)
        sizes = {"big": [4, 4, 4]}
        plan, _ = plan_of(planner, sizes)
        assert {g.kind for g in plan} == {"sharded"}
        planner.forbid_shard("big")
        plan2, by_ns = plan_of(planner, sizes)
        assert [g.kind for g in plan2] == ["single"]
        assert_partition(plan2, by_ns)
        # The pin persists with the telemetry snapshot (a restart must not
        # replay the rejected shards).
        seeded = FetchPlanner(target_series=6)
        seeded.seed(planner.state())
        plan3, _ = plan_of(seeded, sizes)
        assert [g.kind for g in plan3] == ["single"]

    def test_coalescing_respects_pattern_char_budget(self):
        # Series never the bound here (huge target): the char budget alone
        # must split the packing so every coalesced query stays GET-able.
        planner = FetchPlanner(target_series=1 << 20)
        plan, by_ns = plan_of(planner, {f"namespace-{i:04d}": [1] for i in range(800)})
        assert_partition(plan, by_ns)
        coalesced = [g for g in plan if g.kind == "coalesced"]
        assert len(coalesced) >= 2
        for group in coalesced:
            pattern = "|".join(re.escape(ns) for ns in group.namespaces)
            assert len(pattern) <= FetchPlanner.PATTERN_CHAR_BUDGET


# ------------------------------------------------------------------ limiter
class TestAdaptiveLimiter:
    def test_disabled_is_a_plain_semaphore(self):
        async def run():
            limiter = AdaptiveLimiter(2, enabled=False)
            await limiter.acquire()
            await limiter.acquire()
            assert limiter.inflight == 2
            third = asyncio.ensure_future(limiter.acquire())
            await asyncio.sleep(0.01)
            assert not third.done()  # gated at max
            limiter.release()
            await asyncio.sleep(0.01)
            assert third.done()
            limiter.note(ttfb=100.0, queued=1.0, failed=True)  # no-op
            assert limiter.limit == 2.0
            limiter.release()
            limiter.release()

        asyncio.run(run())

    def test_additive_increase_needs_queueing_demand(self):
        limiter = AdaptiveLimiter(8, enabled=True, clock=lambda: 0.0)
        limiter.limit = 2.0
        limiter.note(ttfb=0.01, queued=0.0, failed=False)  # no demand
        assert limiter.limit == 2.0 and limiter.increases == 0
        # Microsecond queue_wait is the uncontended acquire's measurement
        # overhead, not demand — it must not grow the limit (a ">0" gate
        # would be vacuously true on every production completion).
        limiter.note(ttfb=0.01, queued=0.0005, failed=False)
        assert limiter.limit == 2.0 and limiter.increases == 0
        limiter.note(ttfb=0.01, queued=0.5, failed=False)
        assert limiter.limit == 3.0 and limiter.increases == 1
        limiter.limit = 8.0
        limiter.note(ttfb=0.01, queued=0.5, failed=False)  # at max: no growth
        assert limiter.limit == 8.0

    def test_halving_is_cooldown_limited(self):
        now = [0.0]
        limiter = AdaptiveLimiter(8, enabled=True, cooldown=1.0, clock=lambda: now[0])
        limiter.note(ttfb=None, queued=0.0, failed=True)
        assert limiter.limit == 4.0 and limiter.decreases == 1
        limiter.note(ttfb=None, queued=0.0, failed=True)  # inside cooldown
        assert limiter.limit == 4.0 and limiter.decreases == 1
        now[0] = 2.0
        limiter.note(ttfb=None, queued=0.0, failed=True)
        assert limiter.limit == 2.0
        now[0] = 4.0
        limiter.note(ttfb=None, queued=0.0, failed=True)
        now[0] = 6.0
        limiter.note(ttfb=None, queued=0.0, failed=True)
        assert limiter.limit == 1.0  # floor

    def test_ttfb_blowup_degrades_and_baseline_relaxes(self):
        now = [0.0]
        limiter = AdaptiveLimiter(8, enabled=True, cooldown=0.0, clock=lambda: now[0])
        limiter.note(ttfb=0.05, queued=0.0, failed=False)
        assert limiter.baseline_ttfb == pytest.approx(0.05)
        assert limiter.limit == 8.0
        # 10x the baseline (and past the absolute floor): halve.
        limiter.note(ttfb=0.5, queued=0.0, failed=False)
        assert limiter.limit == 4.0
        # The ratchet relaxes upward on every non-improving observation, so
        # a durably slower regime re-baselines instead of halving forever.
        for _ in range(40):
            now[0] += 1.0
            limiter.note(ttfb=0.5, queued=0.0, failed=False)
        assert limiter.baseline_ttfb > 0.15
        # And a fast observation ratchets it straight back down.
        limiter.note(ttfb=0.02, queued=0.0, failed=False)
        assert limiter.baseline_ttfb == pytest.approx(0.02)

    def test_decrease_gates_new_acquires_and_wake_on_increase(self):
        async def run():
            limiter = AdaptiveLimiter(4, enabled=True, clock=lambda: 0.0)
            for _ in range(4):
                await limiter.acquire()
            limiter.note(ttfb=None, queued=0.0, failed=True)  # limit -> 2
            waiter = asyncio.ensure_future(limiter.acquire())
            limiter.release()  # inflight 3 >= limit 2: still gated
            await asyncio.sleep(0.01)
            assert not waiter.done()
            limiter.release()
            limiter.release()  # inflight 1 < 2: wakes
            await asyncio.sleep(0.01)
            assert waiter.done()
            limiter.release()
            limiter.release()

        asyncio.run(run())


class TestLimiterVerdictClassification:
    """`_instrumented`'s AIMD verdict only counts CONGESTION as failure:
    transport/5xx-exhausted or retried ladders halve the limit; a 4xx
    answer (liveness — e.g. the 422 sample-limit that rides the designed
    halved-window retry) and a breaker fast-fail (zero I/O) must not."""

    def _instrument(self, prom, attempt_fn):
        async def run():
            return await prom._instrumented(
                "q", 0.0, 60.0, "30s", "raw", attempt_fn, _QueryMeter()
            )

        return asyncio.run(run())

    def test_4xx_answer_does_not_halve(self):
        prom = PrometheusLoader(Config(quiet=True), cluster="t")

        async def answer_422():
            return 422, None, b"query processing would load too many samples"

        with pytest.raises(PrometheusQueryError):
            self._instrument(prom, answer_422)
        assert prom._limiter.decreases == 0
        assert prom._limiter.limit == prom._limiter.max

    def test_breaker_fast_fail_does_not_halve(self):
        prom = PrometheusLoader(
            Config(quiet=True, prometheus_breaker_threshold=1), cluster="t"
        )
        prom.breaker.record_failure(False, epoch=prom.breaker.success_epoch)

        async def unreachable():  # pragma: no cover - breaker raises first
            raise AssertionError("open breaker must not reach transport")

        with pytest.raises(BreakerOpenError):
            self._instrument(prom, unreachable)
        assert prom._limiter.decreases == 0
        assert prom._limiter.limit == prom._limiter.max

    def test_auth_refresh_retry_does_not_halve(self):
        """The free 401 refresh-and-retry is an expired token, not backend
        distress: every in-flight query takes it at once, and counting it
        as a failed ladder would serialize a perfectly healthy scan."""
        prom = PrometheusLoader(Config(quiet=True), cluster="t")
        prom._auth_refresh = lambda: {}
        answers = iter([(401, None, b"token expired"), (200, "ok", b"")])

        async def attempt():
            return next(answers)

        assert self._instrument(prom, attempt) == "ok"
        assert prom._limiter.decreases == 0
        assert prom._limiter.limit == prom._limiter.max

    def test_5xx_exhaustion_still_halves(self):
        prom = PrometheusLoader(
            Config(quiet=True, prometheus_backoff_cap_seconds=0.01), cluster="t"
        )

        async def answer_500():
            return 500, None, b"overloaded"

        with pytest.raises(PrometheusQueryError):
            self._instrument(prom, answer_500)
        assert prom._limiter.decreases == 1
        assert prom._limiter.limit == prom._limiter.max / 2


class TestShardRejectionPinsSingle:
    def test_non_transient_shard_rejection_pins_namespace(self):
        """A 4xx answer to the shard shape itself (canonically 403: the
        shard's pod-regex forces POST, which read-only RBAC on the
        apiserver service proxy rejects) degrades per-workload THIS scan
        and pins the namespace to the fixed single shape for the next —
        otherwise the planner would rebuild the same failing shards and
        repeat the fallback storm every tick."""
        from types import SimpleNamespace

        prom = PrometheusLoader(
            Config(quiet=True, fetch_plan_target_series=6), cluster="t"
        )
        objects = [
            SimpleNamespace(namespace="big", pods=[f"wl{w}-{i}" for i in range(4)])
            for w in range(3)
        ]
        fallback_rows: set = set()

        async def per_workload(i, obj, resource):
            fallback_rows.add(i)

        async def per_group(group, resource, points_divisor=1):
            assert group.kind == "sharded"
            raise PrometheusQueryError(403, "POST is not allowed on the proxy")

        asyncio.run(prom._fan_out(objects, per_workload, per_group))
        assert prom.planner.telemetry["big"].get("no_shard")
        assert fallback_rows == {0, 1, 2}  # this scan degraded per-workload
        plan = prom.planner.plan({"big": [0, 1, 2]}, [4, 4, 4])
        assert [g.kind for g in plan] == ["single"]


class TestShardRegexMemo:
    def test_shard_regex_built_once_per_group_and_cleared_by_key(self):
        """The shard pod-regex (~hundreds of KB at fleet width) is derived
        purely from the group's indices, so `_group_query` must reuse it
        across resources and halved retries instead of re-sorting and
        re-joining every call."""
        from types import SimpleNamespace

        prom = PrometheusLoader(Config(quiet=True), cluster="t")
        objects = [
            SimpleNamespace(namespace="big", pods=[f"wl{w}-{i}" for i in range(3)])
            for w in range(2)
        ]
        group = PlanGroup("sharded", ("big",), (0, 1), shard=(0, 1))
        query = prom._group_query(ResourceType.CPU, group, objects)
        assert re.escape("wl0-0") + "|" in query
        # Poison the cached entry: a second call (other resource — same
        # group) must REUSE it, proving no rebuild happened.
        (key,) = prom._shard_regexes
        prom._shard_regexes[key] = "SENTINEL"
        assert "SENTINEL" in prom._group_query(ResourceType.Memory, group, objects)


# ---------------------------------------------------------------- sink pump
class CollectingSink:
    def __init__(self, fail_at: int = -1, delay: float = 0.0):
        self.chunks: list = []
        self.fail_at = fail_at
        self.delay = delay
        self.aborted = False

    def feed(self, chunk: bytes) -> None:
        if self.delay:
            time.sleep(self.delay)
        if len(self.chunks) == self.fail_at:
            raise ValueError("malformed Prometheus stream")
        self.chunks.append(bytes(chunk))

    def abort(self) -> None:
        self.aborted = True


class ViewSink(CollectingSink):
    def feed_view(self, buf, n: int) -> None:
        self.feed(bytes(memoryview(buf)[:n]))


class TestSinkPump:
    PAYLOAD = [bytes([i]) * 300 for i in range(10)]

    def _pump_raw(self, sink, buffers=3, buffer_bytes=512):
        pump = _SinkPump(sink, buffers=buffers, buffer_bytes=buffer_bytes)
        for chunk in self.PAYLOAD:
            buf = pump.acquire_buffer()
            buf[: len(chunk)] = chunk
            pump.commit(buf, len(chunk))
        return pump

    def test_raw_lane_feeds_in_order(self):
        sink = CollectingSink()
        pump = self._pump_raw(sink)
        pump.close()
        assert sink.chunks == self.PAYLOAD

    def test_feed_view_lane_is_taken_when_available(self):
        sink = ViewSink()
        pump = self._pump_raw(sink)
        pump.close()
        assert sink.chunks == self.PAYLOAD

    def test_sink_error_surfaces_and_worker_keeps_draining(self):
        sink = CollectingSink(fail_at=2, delay=0.002)
        pump = _SinkPump(sink, buffers=2, buffer_bytes=512)
        # Feed everything; the worker fails on chunk 3 but must keep
        # draining (discarding) so these commits can never deadlock on a
        # full queue. A commit may surface the error early — that's the
        # reader's abort path, also correct.
        error_surfaced = False
        for chunk in self.PAYLOAD:
            try:
                buf = pump.acquire_buffer()
                buf[: len(chunk)] = chunk
                pump.commit(buf, len(chunk))
            except ValueError:
                error_surfaced = True
                break
        if not error_surfaced:
            with pytest.raises(ValueError, match="malformed"):
                pump.close()
        else:
            pump.abort()  # failure path: no raise
        assert len(sink.chunks) == 2  # nothing fed past the error

    def test_abort_is_quiet_and_idempotent(self):
        sink = CollectingSink(fail_at=0)
        pump = _SinkPump(sink, buffers=2, buffer_bytes=512)
        buf = pump.acquire_buffer()
        buf[:4] = b"xxxx"
        pump.commit(buf, 4)
        pump.abort()
        pump.abort()

    def test_recycle_returns_an_unused_buffer(self):
        sink = CollectingSink()
        pump = _SinkPump(sink, buffers=2, buffer_bytes=512)
        buf = pump.acquire_buffer()
        pump.recycle(buf)  # EOF race: acquired but nothing read
        buf2 = pump.acquire_buffer()
        buf2[:3] = b"abc"
        pump.commit(buf2, 3)
        pump.close()
        assert sink.chunks == [b"abc"]

    def test_httpx_lane_backpressure_and_order(self):
        async def run():
            sink = CollectingSink(delay=0.001)
            pump = _SinkPump(sink, buffers=2, loop=asyncio.get_running_loop())
            for chunk in self.PAYLOAD:
                await pump.awrite(chunk)  # parks on the space event when full
            await asyncio.to_thread(pump.close)
            assert sink.chunks == self.PAYLOAD

        asyncio.run(run())


# ------------------------------------------------------- raw transport pool
class KeepAliveFakePrometheus(PhaseFakePrometheus):
    """Keep-alive twin of the phase fake: many requests per connection,
    connection counting, and a server-side idle reap (``close_idle``) — the
    regime the pool's retry-once contract exists for."""

    def __init__(self, **kwargs):
        self.connections = 0
        self._live: list = []
        self._live_lock = threading.Lock()
        super().__init__(**kwargs)

    def close_idle(self) -> None:
        with self._live_lock:
            victims, self._live = self._live, []
        for conn in victims:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            conn.close()

    def _handle(self, conn: socket.socket) -> None:
        self.connections += 1
        with self._live_lock:
            self._live.append(conn)
        try:
            conn.settimeout(5)
            buf = b""
            while True:
                while b"\r\n\r\n" not in buf:
                    chunk = conn.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
                head, _, buf = buf.partition(b"\r\n\r\n")
                target = head.split(b"\r\n")[0].decode("latin-1").split()[1]
                length = 0
                for line in head.split(b"\r\n")[1:]:
                    if line.lower().startswith(b"content-length:"):
                        length = int(line.split(b":")[1])
                while len(buf) < length:
                    buf += conn.recv(65536)
                buf = buf[length:]
                if target.startswith("/api/v1/query_range"):
                    self.range_requests += 1
                    body = self.RANGE_BODY
                else:
                    body = b'{"status":"success","data":{"result":[]}}'
                conn.sendall(
                    f"HTTP/1.1 200 OK\r\nContent-Type: application/json\r\n"
                    f"Content-Length: {len(body)}\r\n\r\n".encode() + body
                )
        except OSError:
            pass
        finally:
            with self._live_lock:
                if conn in self._live:
                    self._live.remove(conn)
            conn.close()


def transport_request(transport: _RawTransport, sink=None) -> int:
    chunks: list = []
    status, data = transport.request_streaming(
        "GET", "/api/v1/query_range?query=up&start=0&end=60&step=60s", None, {},
        sink=sink if sink is not None else chunks.append,
    )
    assert status == 200
    return sum(len(c) for c in chunks)


class TestRawTransportPooling:
    def test_keepalive_reuses_one_connection(self):
        server = KeepAliveFakePrometheus()
        registry = MetricsRegistry()
        try:
            transport = _RawTransport(server.url, {}, None)
            transport.metrics, transport.cluster = registry, "t"
            for _ in range(3):
                transport_request(transport)
            transport.close()
        finally:
            server.close()
        assert server.connections == 1
        assert registry.value("krr_tpu_prom_connections_opened_total", cluster="t") == 1
        assert registry.value("krr_tpu_prom_connections_reused_total", cluster="t") == 2

    def test_retry_once_on_server_closed_idle_connection(self):
        server = KeepAliveFakePrometheus()
        registry = MetricsRegistry()
        try:
            transport = _RawTransport(server.url, {}, None)
            transport.metrics, transport.cluster = registry, "t"
            transport_request(transport)  # conn now idle in the pool
            server.close_idle()  # the server reaps it (keep-alive timeout)
            time.sleep(0.05)
            n = transport_request(transport)  # must retry on a fresh conn
            assert n == len(server.RANGE_BODY)
            transport.close()
        finally:
            server.close()
        assert server.connections == 2
        # The reaped idle conn was popped (a reuse) and replaced (an open).
        assert registry.value("krr_tpu_prom_connections_opened_total", cluster="t") == 2
        assert registry.value("krr_tpu_prom_connections_reused_total", cluster="t") == 1

    def test_no_transparent_retry_once_the_sink_was_fed(self):
        """A connection that dies MID-BODY must raise, not silently retry —
        the sink already consumed bytes a replay would duplicate."""
        server = KeepAliveFakePrometheus()
        try:
            transport = _RawTransport(server.url, {}, None)
            transport_request(transport)  # healthy first fetch, conn idle

            fed = []

            def murdering_sink(chunk: bytes) -> None:
                fed.append(chunk)
                server.close_idle()  # kill the conn under the read
                raise ConnectionResetError("connection died mid-body")

            with pytest.raises(ConnectionError):
                transport_request(transport, sink=murdering_sink)
            transport.close()
        finally:
            server.close()

    def test_pool_width_under_concurrent_fanout(self):
        server = KeepAliveFakePrometheus()
        registry = MetricsRegistry()
        workers = 4
        try:
            transport = _RawTransport(server.url, {}, None)
            transport.metrics, transport.cluster = registry, "t"
            barrier = threading.Barrier(workers)
            errors: list = []

            def worker():
                try:
                    barrier.wait(timeout=5)
                    for _ in range(3):
                        transport_request(transport)
                except Exception as e:  # pragma: no cover - surfaced below
                    errors.append(e)

            threads = [threading.Thread(target=worker) for _ in range(workers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            transport.close()
        finally:
            server.close()
        assert not errors
        # Pool invariant: never more connections than peak concurrency, and
        # the remaining requests rode reuses.
        assert 1 <= server.connections <= workers
        opened = registry.value("krr_tpu_prom_connections_opened_total", cluster="t")
        reused = registry.value("krr_tpu_prom_connections_reused_total", cluster="t")
        assert opened == server.connections
        assert opened + reused == workers * 3


# --------------------------------------------------- plan engagement + exactness
@pytest.fixture(scope="module")
def plan_env(tmp_path_factory):
    """A fleet shaped to make BOTH planner transforms fire at tiny targets:
    'big' (3 workloads x 4 pods = 12 routed series) shards, the three
    one-pod namespaces coalesce."""
    cluster = FakeCluster()
    metrics = FakeMetrics()
    rng = np.random.default_rng(1234)

    def series_for(namespace: str, pods: "list[str]") -> None:
        for pod in pods:
            metrics.set_series(
                namespace, "main", pod,
                cpu=rng.gamma(2.0, 0.05, 48), memory=rng.uniform(5e7, 4e8, 48),
            )

    for w in range(3):
        series_for("big", cluster.add_workload_with_pods(
            "Deployment", f"bigwl-{w}", "big", pod_count=4))
    for ns in ("s1", "s2", "s3"):
        series_for(ns, cluster.add_workload_with_pods(
            "Deployment", f"{ns}-app", ns, pod_count=1))

    server = ServerThread(FakeBackend(cluster, metrics)).start()
    kubeconfig = tmp_path_factory.mktemp("plan") / "config"
    kubeconfig.write_text(yaml.dump({
        "current-context": "fake",
        "contexts": [{"name": "fake", "context": {"cluster": "fake", "user": "u"}}],
        "clusters": [{"name": "fake", "cluster": {"server": server.url}}],
        "users": [{"name": "u", "user": {"token": "t"}}],
    }))
    yield {"server": server, "metrics": metrics, "kubeconfig": str(kubeconfig)}
    server.stop()


def plan_config(env, **overrides) -> Config:
    defaults = dict(
        kubeconfig=env["kubeconfig"],
        prometheus_url=env["server"].url,
        quiet=True,
        format="json",
        # Tiny plan targets so the toy fleet exercises BOTH transforms.
        fetch_plan_target_series=6,
    )
    defaults.update(overrides)
    return Config(**defaults)


def gather(config, objects, registry=None, digests=False):
    async def fetch():
        prom = PrometheusLoader(config, cluster="fake", metrics=registry)
        try:
            if digests:
                return await prom.gather_fleet_digests(
                    objects, 3600, 60, gamma=1.01, min_value=1e-7, num_buckets=128
                ), prom
            return await prom.gather_fleet(objects, 3600, 60), prom
        finally:
            await prom.close()

    return asyncio.run(fetch())


class TestAdaptivePlanBitExact:
    def test_gather_fleet_bitexact_and_counters_fire(self, plan_env):
        objects = asyncio.run(
            KubernetesLoader(plan_config(plan_env)).list_scannable_objects(["fake"])
        )
        registry = MetricsRegistry()
        adaptive, loader = gather(plan_config(plan_env), objects, registry)
        fixed, _ = gather(plan_config(plan_env, fetch_plan="fixed"), objects)
        for resource in ResourceType:
            for i in range(len(objects)):
                assert adaptive[resource][i].keys() == fixed[resource][i].keys(), objects[i]
                for pod in adaptive[resource][i]:
                    np.testing.assert_array_equal(
                        adaptive[resource][i][pod], fixed[resource][i][pod]
                    )
        # Both transforms engaged and are visible on /metrics.
        kinds = {g.kind for g in loader.planner.last_plan}
        assert kinds == {"sharded", "coalesced"}
        assert registry.value("krr_tpu_fetch_plan_coalesced_total", cluster="fake") >= 1
        assert registry.value("krr_tpu_fetch_plan_sharded_total", cluster="fake") >= 2
        # Sampled on release as well as acquire: after the scan settles the
        # gauge must have decayed to 0, not frozen at an in-scan count.
        assert registry.value("krr_tpu_prom_inflight", cluster="fake") == 0

    def test_gather_fleet_digests_bitexact_streamed_and_buffered(self, plan_env, monkeypatch):
        objects = asyncio.run(
            KubernetesLoader(plan_config(plan_env)).list_scannable_objects(["fake"])
        )
        adaptive, _ = gather(plan_config(plan_env), objects, digests=True)
        fixed, _ = gather(plan_config(plan_env, fetch_plan="fixed"), objects, digests=True)
        for attr in ("cpu_counts", "cpu_total", "cpu_peak", "mem_total", "mem_peak"):
            np.testing.assert_array_equal(getattr(adaptive, attr), getattr(fixed, attr))
        from krr_tpu.integrations import native

        monkeypatch.setattr(native, "stream_available", lambda: False)
        buffered, _ = gather(plan_config(plan_env), objects, digests=True)
        for attr in ("cpu_counts", "cpu_total", "cpu_peak", "mem_total", "mem_peak"):
            np.testing.assert_array_equal(getattr(adaptive, attr), getattr(buffered, attr))

    def test_cold_runner_scan_bitexact_vs_fixed_plan(self, plan_env):
        """The end-to-end leg: a full cold Runner scan (digest ingest,
        streamed pipeline) renders byte-identical output under both plans."""
        import contextlib
        import io

        from krr_tpu.core.runner import Runner

        def run_scan(**overrides) -> str:
            config = plan_config(
                plan_env,
                strategy="tdigest",
                other_args={"digest_ingest": True},
                scan_end_timestamp=1_700_100_000.0,
                **overrides,
            )
            runner = Runner(config)
            with contextlib.redirect_stdout(io.StringIO()):
                result = asyncio.run(runner.run())
            return result.format("json")

        assert run_scan() == run_scan(fetch_plan="fixed")

    def test_coalesced_failure_decomposes_to_member_namespaces(self, plan_env):
        """One broken member of a coalesced group must degrade like the
        fixed plan — its own namespace only. The group decomposes into
        per-namespace singles (healthy siblings keep their batched shape)
        instead of dropping EVERY member to per-workload queries."""

        class RecordingLogger:
            def __init__(self):
                self.lines: list = []

            def warning(self, msg, *a, **k):
                self.lines.append(str(msg))

            info = debug = error = warning

        logger = RecordingLogger()
        config = plan_config(
            plan_env,
            prometheus_backoff_cap_seconds=0.02,
            prometheus_retry_deadline_seconds=0.2,
        )
        objects = asyncio.run(KubernetesLoader(config).list_scannable_objects(["fake"]))
        metrics = plan_env["metrics"]
        metrics.fail_namespaces = frozenset({"s1"})
        try:
            async def fetch():
                prom = PrometheusLoader(config, cluster="fake", logger=logger)
                try:
                    return await prom.gather_fleet(objects, 3600, 60)
                finally:
                    await prom.close()

            adaptive = asyncio.run(fetch())
        finally:
            metrics.fail_namespaces = frozenset()
        by_key = {(o.namespace, o.name): i for i, o in enumerate(objects)}
        # Healthy coalesced siblings still fetched; the broken member is
        # empty (UNKNOWN), exactly the fixed plan's failure domain.
        for ns in ("s2", "s3"):
            assert adaptive[ResourceType.CPU][by_key[(ns, f"{ns}-app")]]
        assert not adaptive[ResourceType.CPU][by_key[("s1", "s1-app")]]
        assert any("decomposing into" in line for line in logger.lines), logger.lines
        # The only per-workload fallbacks are s1's own objects — never a
        # coalesced sibling's.
        fallbacks = [l for l in logger.lines if "falling back to per-workload" in l]
        assert fallbacks and all(
            "s1" in l and "s2" not in l and "s3" not in l for l in fallbacks
        ), fallbacks

    def test_second_scan_plans_from_observed_telemetry(self, plan_env):
        """Scan 1 observes per-namespace series/bytes; scan 2's plan uses
        them (state() is non-empty and seeds an equal-shape plan)."""
        objects = asyncio.run(
            KubernetesLoader(plan_config(plan_env)).list_scannable_objects(["fake"])
        )
        _, loader = gather(plan_config(plan_env), objects)
        state = loader.planner.state()
        assert set(state["namespaces"]) >= {"s1", "s2", "s3"}
        assert all(v.get("series") for v in state["namespaces"].values())
        # A fresh loader seeded with the snapshot plans the same shapes.
        seeded = FetchPlanner(target_series=6)
        seeded.seed(state)
        by_namespace: dict = {}
        for i, obj in enumerate(objects):
            by_namespace.setdefault(obj.namespace, []).append(i)
        pods = [len(obj.pods) for obj in objects]
        assert seeded.plan(by_namespace, pods) == loader.planner.plan(by_namespace, pods)

    def test_count_probe_rides_post_past_get_limit(self, plan_env):
        """A shard-scale ``count()`` probe whose query overflows the GET
        cut-over must ride POST and still return the true series count — a
        GET there earns a 414 and silently forfeits the window-sizing
        bound (the fake enforces the same request-line cap)."""
        config = plan_config(plan_env)
        objects = asyncio.run(KubernetesLoader(config).list_scannable_objects(["fake"]))
        big_pods = sorted({p for o in objects if o.namespace == "big" for p in o.pods})
        pad = [f"ghost-{i:05d}" for i in range(600)]
        query = cpu_namespace_shard_query("big", "|".join(map(re.escape, big_pods + pad)))
        assert len(query) > PrometheusLoader.GET_QUERY_LIMIT

        async def probe():
            prom = PrometheusLoader(config, cluster="fake")
            try:
                await prom._ensure_connected()
                return await prom._count_series(query, time.time())
            finally:
                await prom.close()

        assert asyncio.run(probe()) == len(big_pods)


class TestServeAdaptiveBitExact:
    """The serve legs of the bit-exactness criterion: clean incremental
    ticks AND quarantine catch-up legs, adaptive vs the fixed escape hatch,
    through the real composition (chaos harness: real PrometheusLoader over
    HTTP against the archetype fleet — five small namespaces, so the
    adaptive plan coalesces every tick)."""

    TICK = 300.0

    @pytest.fixture(scope="class")
    def serve_env(self, tmp_path_factory):
        from .fakes.chaos import ServerThread as ChaosServerThread
        from .fakes.chaos import build_fleet, write_kubeconfig

        fleet = build_fleet(samples=240, seed=23)
        server = ChaosServerThread(fleet.backend).start()
        kubeconfig = write_kubeconfig(
            tmp_path_factory.mktemp("fetchplan-serve") / "config", server.url
        )
        yield {"fleet": fleet, "server": server, "kubeconfig": kubeconfig}
        server.stop()

    def _config(self, env, **overrides) -> Config:
        defaults = dict(
            kubeconfig=env["kubeconfig"],
            prometheus_url=env["server"].url,
            strategy="tdigest",
            quiet=True,
            server_port=0,
            scan_interval_seconds=self.TICK,
            # Comparison semantics (mirrors test_chaos): raw recomputes
            # publish verbatim, breaker parked out of the way, fast ladders.
            hysteresis_enabled=False,
            prometheus_breaker_threshold=100,
            prometheus_breaker_cooldown_seconds=0.02,
            prometheus_retry_deadline_seconds=2.0,
            prometheus_backoff_cap_seconds=0.25,
            # depth 1 → pipeline batches of ~5 workloads, so each batch
            # spans multiple archetype namespaces and the planner has
            # something to coalesce (the streamed pipeline never splits a
            # namespace, but at the default depth this 10-workload fleet
            # degenerates to one-namespace batches — nothing to plan over).
            pipeline_depth=1,
            other_args={"history_duration": 1, "timeframe_duration": 1},
        )
        defaults.update(overrides)
        return Config(**defaults)

    def _soak(self, env, timeline=None, **overrides):
        from .fakes.chaos import run_soak

        return asyncio.run(
            run_soak(
                self._config(env, **overrides), env["fleet"].backend, timeline,
                ticks=6, tick_seconds=self.TICK,
            )
        )

    def test_clean_incremental_ticks_bitexact_vs_fixed_plan(self, serve_env):
        from .fakes.chaos import stores_bitexact

        adaptive = self._soak(serve_env)
        fixed = self._soak(serve_env, fetch_plan="fixed")
        assert [t.ok for t in adaptive.ticks] == [True] * 6
        equal, detail = stores_bitexact(adaptive.store, fixed.store)
        assert equal, detail
        assert adaptive.state.peek().body_json == fixed.state.peek().body_json
        # The adaptive soak really coalesced (five small archetype
        # namespaces per tick) — not a vacuous comparison.
        assert adaptive.metrics.total("krr_tpu_fetch_plan_coalesced_total") >= 6

    def test_quarantine_catchup_legs_bitexact_vs_fixed_plan(self, serve_env):
        from .fakes.chaos import FaultSpec, FaultTimeline, stores_bitexact

        timeline = lambda: FaultTimeline(  # noqa: E731 - fresh per soak
            [(2, 4, FaultSpec(fail_namespaces=frozenset({"diurnal"})))]
        )
        adaptive = self._soak(serve_env, timeline())
        fixed = self._soak(serve_env, timeline(), fetch_plan="fixed")
        # Both degraded through the outage and recovered via catch-up...
        assert adaptive.counts()["degraded"] >= 1
        assert adaptive.counts()["aborted"] == 0
        # ...and the catch-up legs (which fetch through the SAME planned
        # fan-out) converged both stores to the identical state.
        equal, detail = stores_bitexact(adaptive.store, fixed.store)
        assert equal, detail
        assert adaptive.state.peek().body_json == fixed.state.peek().body_json


class TestSessionPlanPersistence:
    def test_session_snapshot_and_seed_round_trip(self):
        from krr_tpu.core.runner import ScanSession

        class StubSource:
            def __init__(self):
                self.planner = FetchPlanner()
                self.planner.observe("ns-a", series=12.0, bytes_seen=4096.0)

        session = ScanSession.__new__(ScanSession)
        session._history_sources = {None: StubSource(), "c2": StubSource()}
        session._plan_seeds = {}
        states = session.fetch_plan_states()
        assert set(states) == {"default", "c2"}
        assert states["default"]["namespaces"]["ns-a"]["series"] == pytest.approx(12.0)
        session.seed_fetch_plans(states)
        assert session._plan_seeds["c2"]["namespaces"]["ns-a"]["series"] == pytest.approx(12.0)
        session.seed_fetch_plans(None)  # no seeds: keep the previous ones
        assert session._plan_seeds
