"""Dependency-free SLO engine: declarative objectives, rolling windows,
fast/slow burn-rate alerts.

``GET /metrics`` is a firehose; deciding whether the server is HEALTHY from
it requires a human (or an external Prometheus with hand-written alert
rules neither the CLI nor CI has). This module closes the loop in-process,
with the same shape SRE practice converged on for error budgets:

* An :class:`Objective` declares a service-level objective as an allowed
  **bad fraction** (the error budget): scan failure ratio, fetch failed-row
  ratio, scan latency, freshness. Each evaluation samples cumulative
  ``(bad, total)`` event counts — ratio objectives read counters off the
  shared :class:`~krr_tpu.obs.metrics.MetricsRegistry`; threshold
  objectives (latency, freshness) contribute one good/bad event per
  evaluation by comparing an instantaneous value against a limit.

* The :class:`SloEngine` keeps a rolling ring of timestamped samples per
  objective and computes the **burn rate** over two windows: the windowed
  bad ratio divided by the budget (burn 1.0 = consuming exactly the budget;
  burn 20 = a full outage against a 5 % budget). An alert FIRES when both
  the fast and the slow burn exceed their thresholds AND the slow window
  holds at least ``min_slow_bad_events`` bad events — the fast window makes
  detection quick, the slow window keeps a brief blip from paging, and the
  event floor keeps the ratios honest at coarse tick cadences (at a 900 s
  scan interval the slow window holds only ~4 samples, so without the floor
  a single transient failure would clear both ratio thresholds). The alert
  RESOLVES as soon as the firing condition no longer holds (the fast window
  slides clean first, so recovery is detected at fast-window speed).

* Transitions fire structured log lines and ``krr_tpu_slo_*`` metrics; the
  serve scheduler evaluates once per tick, ``GET /statusz`` renders the
  current posture (read-only — a scrape must not skew the tick-cadenced
  event stream), and ``/healthz`` downgrades its verdict to ``degraded``
  while any alert is firing.

Everything here is host arithmetic over a handful of floats per tick — no
background task, no locking (evaluations run on the event loop; /statusz
reads are pure).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from krr_tpu.obs.metrics import MetricsRegistry

#: Allowed violation fraction for threshold objectives (latency,
#: freshness): up to 10% of evaluations may breach the limit before the
#: budget is spent. Ratio objectives carry their own budget knobs.
THRESHOLD_BUDGET = 0.1


@dataclass
class Objective:
    """One service-level objective.

    ``sample`` returns cumulative ``(bad, total)`` event counts for ratio
    objectives (monotone, read off counters). Threshold objectives instead
    set ``value``/``limit``: each evaluation reads the instantaneous value
    and counts one event, bad iff ``value > limit``. A ``None`` value means
    "nothing to observe this round" and records NO event — freshness before
    the first publish (the /healthz ``starting`` verdict owns that regime),
    or scan latency on a tick where no new scan completed (re-counting a
    stale gauge would turn one slow scan into a window full of bad events,
    and one fast scan into dilution that masks real ones)."""

    name: str
    description: str
    budget: float  # allowed bad fraction, in (0, 1]
    sample: Optional[Callable[[], tuple[float, float]]] = None
    value: Optional[Callable[[], Optional[float]]] = None
    limit: Optional[float] = None

    def __post_init__(self) -> None:
        if not 0.0 < self.budget <= 1.0:
            raise ValueError(f"objective {self.name}: budget must be in (0, 1]")
        if (self.sample is None) == (self.value is None):
            raise ValueError(
                f"objective {self.name}: exactly one of sample= (ratio) or "
                f"value=/limit= (threshold) must be set"
            )


@dataclass
class _AlertState:
    firing: bool = False
    since: Optional[float] = None
    #: Event totals accumulated by threshold objectives (ratio objectives
    #: read cumulative counters directly).
    bad: float = 0.0
    total: float = 0.0
    #: (ts, bad_cum, total_cum) samples, newest last.
    samples: deque = field(default_factory=deque)
    last_value: Optional[float] = None


class SloEngine:
    """Evaluates objectives over rolling windows and manages alert state."""

    def __init__(
        self,
        objectives: "list[Objective]",
        metrics: Optional[MetricsRegistry] = None,
        *,
        fast_window_seconds: float = 300.0,
        slow_window_seconds: float = 3600.0,
        fast_burn_threshold: float = 10.0,
        slow_burn_threshold: float = 5.0,
        min_slow_bad_events: int = 2,
        clock: Callable[[], float] = time.time,
        logger=None,
    ) -> None:
        self.objectives = list(objectives)
        self.metrics = metrics
        self.fast_window_seconds = float(fast_window_seconds)
        self.slow_window_seconds = max(float(slow_window_seconds), self.fast_window_seconds)
        self.fast_burn_threshold = float(fast_burn_threshold)
        self.slow_burn_threshold = float(slow_burn_threshold)
        self.min_slow_bad_events = max(1, int(min_slow_bad_events))
        self.clock = clock
        self.logger = logger
        self._state: dict[str, _AlertState] = {}
        now = float(clock())
        for objective in self.objectives:
            state = _AlertState()
            # Zero baseline: the first evaluation's window then covers
            # everything since engine construction (counters start at 0 for
            # a fresh process; a one-shot --statusz evaluation sees the
            # whole scan).
            state.samples.append((now, 0.0, 0.0))
            self._state[objective.name] = state

    def add_objective(self, objective: Objective, now: Optional[float] = None) -> None:
        """Register an objective after construction (the serve composition
        root appends the optional sentinel ``scan_regressions`` objective
        once the sentinel exists) — same zero-baseline seeding as the
        constructor, so its first evaluation covers everything since
        registration."""
        self.objectives.append(objective)
        state = _AlertState()
        state.samples.append((float(self.clock()) if now is None else float(now), 0.0, 0.0))
        self._state[objective.name] = state

    # ----------------------------------------------------------- sampling
    def _sample(self, objective: Objective, state: _AlertState) -> None:
        if objective.sample is not None:
            bad, total = objective.sample()
            state.bad, state.total = float(bad), float(total)
            state.last_value = None
            return
        value = objective.value() if objective.value is not None else None
        if value is None:
            return  # nothing to observe this round: no event either way
        state.last_value = value
        violated = objective.limit is not None and value > objective.limit
        state.bad += 1.0 if violated else 0.0
        state.total += 1.0

    @staticmethod
    def _window_delta(samples: deque, now: float, window: float) -> tuple[float, float]:
        """``(bad, total)`` events inside ``[now - window, now]`` — deltas
        against the newest sample at or before the window start (or the
        oldest retained, for engines younger than the window)."""
        _newest_ts, newest_bad, newest_total = samples[-1]
        baseline = samples[0]
        cutoff = now - window
        for sample in samples:
            if sample[0] <= cutoff:
                baseline = sample
            else:
                break
        return max(0.0, newest_bad - baseline[1]), newest_total - baseline[2]

    @classmethod
    def _window_ratio(cls, samples: deque, now: float, window: float) -> float:
        bad, total = cls._window_delta(samples, now, window)
        return bad / total if total > 0 else 0.0

    def _prune(self, state: _AlertState, now: float) -> None:
        # Keep one sample at or before the slow-window start as the
        # baseline; everything older is dead weight.
        cutoff = now - self.slow_window_seconds
        samples = state.samples
        while len(samples) >= 2 and samples[1][0] <= cutoff:
            samples.popleft()

    # --------------------------------------------------------- evaluation
    def evaluate(self, now: Optional[float] = None) -> "list[dict]":
        """Sample every objective, update burn rates and alert states, fire
        metrics and transition logs. Returns the transitions (dicts with
        ``objective``/``to``), mostly for tests."""
        now = float(self.clock()) if now is None else float(now)
        transitions: list[dict] = []
        for objective in self.objectives:
            state = self._state[objective.name]
            self._sample(objective, state)
            state.samples.append((now, state.bad, state.total))
            self._prune(state, now)
            fast, slow = self._burns(objective, state, now)
            slow_bad, _ = self._window_delta(state.samples, now, self.slow_window_seconds)
            firing = (
                fast >= self.fast_burn_threshold
                and slow >= self.slow_burn_threshold
                # Ratios alone lie at coarse tick cadences (4 samples/hour
                # at the default serve interval): a SINGLE bad event is a
                # blip, never sustained burn, no matter how high its ratio.
                and slow_bad >= self.min_slow_bad_events
            )
            if firing != state.firing:
                state.firing = firing
                state.since = now
                to = "firing" if firing else "resolved"
                transitions.append({"objective": objective.name, "to": to, "at": now})
                if self.metrics is not None:
                    self.metrics.inc(
                        "krr_tpu_slo_alert_transitions_total", objective=objective.name, to=to
                    )
                if self.logger is not None:
                    message = (
                        f"SLO alert {to}: {objective.name} burn fast={fast:.1f} "
                        f"slow={slow:.1f} (budget {objective.budget:g}, thresholds "
                        f"{self.fast_burn_threshold:g}/{self.slow_burn_threshold:g})"
                    )
                    (self.logger.warning if firing else self.logger.info)(message)
            if self.metrics is not None:
                self.metrics.set(
                    "krr_tpu_slo_burn_rate", fast, objective=objective.name, window="fast"
                )
                self.metrics.set(
                    "krr_tpu_slo_burn_rate", slow, objective=objective.name, window="slow"
                )
                slow_ratio = self._window_ratio(state.samples, now, self.slow_window_seconds)
                self.metrics.set(
                    "krr_tpu_slo_error_budget_remaining",
                    1.0 - slow_ratio / objective.budget,
                    objective=objective.name,
                )
                self.metrics.set(
                    "krr_tpu_slo_alert_firing",
                    1.0 if state.firing else 0.0,
                    objective=objective.name,
                )
        return transitions

    def _burns(
        self, objective: Objective, state: _AlertState, now: float
    ) -> tuple[float, float]:
        fast = self._window_ratio(state.samples, now, self.fast_window_seconds) / objective.budget
        slow = self._window_ratio(state.samples, now, self.slow_window_seconds) / objective.budget
        return fast, slow

    # ------------------------------------------------------------ reading
    def firing(self) -> "list[str]":
        return [o.name for o in self.objectives if self._state[o.name].firing]

    def status(self, now: Optional[float] = None) -> dict:
        """Current posture for ``GET /statusz`` — READ-ONLY (burn rates are
        recomputed at ``now`` from the stored samples; no events are
        appended, so scrape traffic can't dilute tick-cadence sampling)."""
        now = float(self.clock()) if now is None else float(now)
        objectives = []
        for objective in self.objectives:
            state = self._state[objective.name]
            fast, slow = self._burns(objective, state, now)
            slow_ratio = self._window_ratio(state.samples, now, self.slow_window_seconds)
            objectives.append(
                {
                    "name": objective.name,
                    "description": objective.description,
                    "budget": objective.budget,
                    "kind": "ratio" if objective.sample is not None else "threshold",
                    "limit": objective.limit,
                    "last_value": state.last_value,
                    "events": {"bad": state.bad, "total": state.total},
                    "burn_rate": {
                        "fast": round(fast, 4),
                        "slow": round(slow, 4),
                        "fast_window_seconds": self.fast_window_seconds,
                        "slow_window_seconds": self.slow_window_seconds,
                    },
                    "error_budget_remaining": round(1.0 - slow_ratio / objective.budget, 4),
                    "firing": state.firing,
                    "since": state.since,
                }
            )
        return {
            "evaluated_at": now,
            "thresholds": {
                "fast_burn": self.fast_burn_threshold,
                "slow_burn": self.slow_burn_threshold,
            },
            "firing": self.firing(),
            "objectives": objectives,
        }

    def render_text(self, now: Optional[float] = None) -> str:
        """The human twin of :meth:`status` (``GET /statusz?format=text``)."""
        status = self.status(now)
        lines = [
            f"krr-tpu SLO status (thresholds: fast burn ≥ "
            f"{status['thresholds']['fast_burn']:g} AND slow burn ≥ "
            f"{status['thresholds']['slow_burn']:g})",
            f"firing: {', '.join(status['firing']) or 'none'}",
            "",
        ]
        for obj in status["objectives"]:
            burn = obj["burn_rate"]
            flag = "FIRING" if obj["firing"] else "ok"
            lines.append(
                f"[{flag:>6}] {obj['name']}: burn fast={burn['fast']:g} "
                f"slow={burn['slow']:g}, budget {obj['budget']:g}, "
                f"budget remaining {obj['error_budget_remaining']:g}"
            )
            detail = f"         {obj['description']}"
            if obj["kind"] == "threshold":
                value = "n/a" if obj["last_value"] is None else f"{obj['last_value']:g}"
                detail += f" (last value {value}, limit {obj['limit']:g})"
            lines.append(detail)
        return "\n".join(lines) + "\n"


def default_objectives(
    metrics: MetricsRegistry,
    *,
    scan_failure_budget: float,
    fetch_failure_budget: float,
    scan_latency_seconds: float,
    freshness_seconds: float,
    read_p99_seconds: float = 0.0,
    clock: Callable[[], float] = time.time,
) -> "list[Objective]":
    """The stock objective set, fed by the shared registry:

    * ``scan_failures``  — ratio of aborted scans to attempted scans.
    * ``fetch_failed_rows`` — ratio of terminally-failed object fetches.
    * ``scan_latency``   — the last scan's wall (summed legs) vs its limit.
    * ``freshness``      — age of the last published window vs its limit.
    * ``read_p99``       — (opt-in: ``read_p99_seconds`` > 0) the last
      tick's /recommendations p99 latency vs its limit — the read-path SLO
      the bench loadtest leg gates offline.
    """

    def scan_failures() -> tuple[float, float]:
        bad = metrics.total("krr_tpu_scan_failures_total")
        return bad, bad + metrics.total("krr_tpu_scans_total")

    def fetch_failed_rows() -> tuple[float, float]:
        return (
            metrics.total("krr_tpu_fetch_failed_rows_total"),
            metrics.total("krr_tpu_fetch_rows_total"),
        )

    #: Completed-scan count at the last latency observation: the gauge
    #: holds the LAST scan's legs, so without this guard every evaluation
    #: (skipped ticks included) would re-count the same scan as a fresh
    #: good/bad event.
    latency_seen = [0.0]

    def scan_wall() -> Optional[float]:
        count = metrics.total("krr_tpu_scans_total")
        if count <= latency_seen[0]:
            return None  # no NEW completed scan since the last observation
        latency_seen[0] = count
        return metrics.total("krr_tpu_scan_duration_seconds")

    def staleness() -> Optional[float]:
        last = metrics.value("krr_tpu_last_scan_timestamp_seconds")
        if last is None:
            return None
        return float(clock()) - last

    objectives = [
        Objective(
            name="scan_failures",
            description="Scans must complete: aborted scans burn this budget.",
            budget=scan_failure_budget,
            sample=scan_failures,
        ),
        Objective(
            name="fetch_failed_rows",
            description="Object fetches must succeed: rows rendered UNKNOWN burn this budget.",
            budget=fetch_failure_budget,
            sample=fetch_failed_rows,
        ),
        Objective(
            name="scan_latency",
            description="A scan's wall time must fit its cadence.",
            budget=THRESHOLD_BUDGET,
            value=scan_wall,
            limit=scan_latency_seconds,
        ),
        Objective(
            name="freshness",
            description="The published window must stay fresh.",
            budget=THRESHOLD_BUDGET,
            value=staleness,
            limit=freshness_seconds,
        ),
    ]
    if read_p99_seconds > 0:
        # Same stale-gauge guard as scan_latency: the p99 gauge holds the
        # LAST read-serving tick's value, so only a NEW completed scan may
        # contribute an event, and only when that tick actually served
        # reads (krr_tpu_http_read_requests > 0) — a quiet server must not
        # dilute (or burn) the budget with replayed values.
        read_seen = [0.0]

        def read_p99() -> Optional[float]:
            count = metrics.total("krr_tpu_scans_total")
            if count <= read_seen[0]:
                return None
            read_seen[0] = count
            if not (metrics.value("krr_tpu_http_read_requests") or 0.0):
                return None
            return metrics.value("krr_tpu_http_read_p99_seconds")

        objectives.append(
            Objective(
                name="read_p99",
                description=(
                    "GET /recommendations p99 latency must stay under its "
                    "limit: ticks whose read-path p99 breaches it burn this "
                    "budget."
                ),
                budget=THRESHOLD_BUDGET,
                value=read_p99,
                limit=read_p99_seconds,
            )
        )
    return objectives


def engine_from_config(
    metrics: MetricsRegistry,
    config,
    *,
    one_shot: bool = False,
    clock: Callable[[], float] = time.time,
    logger=None,
) -> SloEngine:
    """Build the engine from the ``--slo-*`` knobs (`krr_tpu.core.config`),
    resolving the 0=auto limits against the serve scan cadence: latency
    defaults to one cadence, freshness to three (the /healthz stale
    threshold's shape). A pinned ``--scan-end-timestamp`` (reproducible /
    offline-benchmark scans) drops the freshness objective — the window's
    age is the point of pinning, not a health signal. ``one_shot`` (the
    CLI's single ``--statusz`` evaluation) lowers the min-slow-bad-events
    floor to 1: that floor exists to damp blips across a serve tick stream,
    and one scan can only ever contribute one bad event — a totally failed
    scan must report as firing, not as a "blip"."""
    latency = config.slo_scan_latency_seconds or config.scan_interval_seconds
    freshness = config.slo_freshness_seconds or 3.0 * config.scan_interval_seconds
    objectives = default_objectives(
        metrics,
        scan_failure_budget=config.slo_scan_failure_budget,
        fetch_failure_budget=config.slo_fetch_failure_budget,
        scan_latency_seconds=latency,
        freshness_seconds=freshness,
        read_p99_seconds=getattr(config, "slo_read_p99_seconds", 0.0),
        clock=clock,
    )
    if getattr(config, "scan_end_timestamp", None) is not None:
        objectives = [o for o in objectives if o.name != "freshness"]
    return SloEngine(
        objectives,
        metrics,
        fast_window_seconds=config.slo_fast_window_seconds,
        slow_window_seconds=config.slo_slow_window_seconds,
        fast_burn_threshold=config.slo_fast_burn,
        slow_burn_threshold=config.slo_slow_burn,
        min_slow_bad_events=1 if one_shot else 2,
        clock=clock,
        logger=logger,
    )
