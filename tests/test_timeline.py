"""Scan flight recorder + regression sentinel (`krr_tpu.obs.timeline`,
`krr_tpu.obs.sentinel`).

* Timeline durability: append/reopen bit-exactness, the torn-tail/bit-flip
  truncation property matrix (the durastore discipline on the timeline's
  framing — the recovered file is bit-identical to the original up to the
  last durable record), retention compaction, degrade-on-disk-fault, and
  the read-only ``analyze --trend`` parse.
* Sentinel semantics: warm-up gating, median/MAD band detection, dominant-
  category attribution with phase refinement, poison-proof baselines,
  per-kind regimes, restart seeding, regime-acceptance rebase, and the
  optional SLO objective's event counts.
* Surfacing: ``GET /debug/timeline`` (and the shared ``?n=`` validation on
  all three debug routes), the ``/statusz`` trend section, the SIGUSR2
  trend artifact, and the ``analyze --trend`` / empty-ring CLI paths.
"""

import asyncio
import json
import struct

import numpy as np
import pytest

from krr_tpu.obs.metrics import MetricsRegistry
from krr_tpu.obs.sentinel import RegressionSentinel, render_trend_text, trend_report
from krr_tpu.obs.timeline import TIMELINE_MAGIC, ScanTimeline, build_scan_record
from krr_tpu.obs.trace import Tracer

from .fakes.chaos import FaultyFs

BASE_CATEGORIES = {
    "fetch_transport": 0.5,
    "fetch_decode": 0.1,
    "fetch_backoff": 0.0,
    "fetch_other": 0.05,
    "fold": 0.1,
    "compute": 0.2,
    "discover": 0.02,
    "publish": 0.03,
    "other": 0.0,
    "idle": 0.05,
}


def make_record(i: int, kind: str = "delta", categories: dict | None = None, phases: dict | None = None, **overrides) -> dict:
    cats = dict(BASE_CATEGORIES)
    cats.update(categories or {})
    record = {
        "v": 1,
        "ts": 1_000_000.0 + i * 300.0,
        "scan_id": f"scan-{i}",
        "kind": kind,
        "wall": round(sum(cats.values()), 6),
        "categories": cats,
        "phases": {"ttfb": 0.3, "body_read": 0.15, "connect": 0.02, **(phases or {})},
        "rows": 8,
        "failed_rows": 0,
        "stale_workloads": 0,
        "wire_bytes": 1 << 20,
        "queries": 4,
        "retries": 0,
        "publish": {"changed": 1, "suppressed": 0},
        "persist": {"seconds": 0.01, "bytes": 512, "epoch": i + 1, "failing": False},
        "plan": {"coalesced": 1, "sharded": 0},
    }
    record.update(overrides)
    return record


def frame_offsets(path: str) -> "tuple[bytes, list[int]]":
    """(file bytes, [end offset of record k] prefixed by the header end) —
    parsed independently of the code under test."""
    blob = open(path, "rb").read()
    offsets = [len(TIMELINE_MAGIC)]
    pos = len(TIMELINE_MAGIC)
    while pos < len(blob):
        length, _crc = struct.unpack_from("<II", blob, pos)
        pos += 8 + length
        offsets.append(pos)
    return blob, offsets


# ------------------------------------------------------------------ timeline
class TestScanTimeline:
    def test_append_reopen_roundtrips_records(self, tmp_path):
        path = str(tmp_path / "timeline.log")
        timeline = ScanTimeline.open(path)
        records = [make_record(i) for i in range(5)]
        for record in records:
            assert timeline.append(record) is True
        timeline.close()
        reopened = ScanTimeline.open(path)
        assert reopened.records() == records
        assert ScanTimeline.read_records(path) == records
        reopened.close()

    def test_torn_tail_matrix_recovers_bit_identical_prefix(self, tmp_path):
        """The acceptance property: for cuts sampled across the whole file
        (record boundaries, ±1 byte, inside the frame header, mid-record),
        recovery keeps exactly the records that remain whole AND the
        recovered file is BIT-identical to the original truncated at the
        last durable record boundary."""
        path = str(tmp_path / "timeline.log")
        timeline = ScanTimeline.open(path)
        records = [make_record(i) for i in range(6)]
        for record in records:
            timeline.append(record)
        timeline.close()
        blob, offsets = frame_offsets(path)
        assert len(offsets) == 7  # header + 6 records

        cuts = set()
        for end in offsets:
            cuts.update({end, end - 1, end + 1, end + 4})
        rng = np.random.default_rng(11)
        cuts.update(int(c) for c in rng.integers(len(TIMELINE_MAGIC), len(blob), 8))
        for cut in sorted(c for c in cuts if len(TIMELINE_MAGIC) <= c <= len(blob)):
            with open(path, "wb") as f:
                f.write(blob[:cut])
            survivors = sum(1 for end in offsets[1:] if end <= cut)
            recovered = ScanTimeline.open(path)
            assert recovered.records() == records[:survivors], f"cut at {cut}"
            recovered.close()
            # Bit-identical to the never-torn file up to the last durable
            # record: truncation cut exactly the torn bytes, nothing else.
            assert open(path, "rb").read() == blob[: offsets[survivors]], f"cut at {cut}"
        with open(path, "wb") as f:
            f.write(blob)

    def test_bitflips_truncate_from_corrupt_record(self, tmp_path):
        path = str(tmp_path / "timeline.log")
        timeline = ScanTimeline.open(path)
        records = [make_record(i) for i in range(4)]
        for record in records:
            timeline.append(record)
        timeline.close()
        blob, offsets = frame_offsets(path)
        rng = np.random.default_rng(13)
        for flip in sorted(int(x) for x in rng.integers(len(TIMELINE_MAGIC), len(blob), 6)):
            corrupted = bytearray(blob)
            corrupted[flip] ^= 0x20
            with open(path, "wb") as f:
                f.write(corrupted)
            survivors = sum(1 for end in offsets[1:] if end <= flip)
            recovered = ScanTimeline.open(path)
            assert recovered.records() == records[:survivors], f"flip at {flip}"
            recovered.close()
            with open(path, "wb") as f:
                f.write(blob)

    def test_flipped_header_resets(self, tmp_path):
        path = str(tmp_path / "timeline.log")
        timeline = ScanTimeline.open(path)
        timeline.append(make_record(0))
        timeline.close()
        blob = bytearray(open(path, "rb").read())
        blob[1] ^= 0xFF
        with open(path, "wb") as f:
            f.write(blob)
        recovered = ScanTimeline.open(path)
        assert recovered.records() == []
        recovered.close()

    def test_retention_compaction_bounds_the_file(self, tmp_path):
        path = str(tmp_path / "timeline.log")
        registry = MetricsRegistry()
        timeline = ScanTimeline.open(path, retain_records=4, metrics=registry)
        for i in range(10):
            timeline.append(make_record(i))
        # 10 > 2*4 → at least one retention rewrite down to the ring.
        assert registry.total("krr_tpu_timeline_compactions_total") >= 1
        assert timeline.records() == [make_record(i) for i in range(6, 10)]
        timeline.close()
        reopened = ScanTimeline.open(path, retain_records=4)
        assert reopened.records() == [make_record(i) for i in range(6, 10)]
        reopened.close()

    def test_open_with_lowered_retention_compacts_and_still_appends(self, tmp_path):
        """Recovery-triggered compaction (the on-disk count exceeds a
        lowered retain_records) must leave exactly one live append handle —
        and appends after it must land durably."""
        path = str(tmp_path / "timeline.log")
        timeline = ScanTimeline.open(path, retain_records=100)
        for i in range(10):
            timeline.append(make_record(i))
        timeline.close()
        reopened = ScanTimeline.open(path, retain_records=3)
        assert reopened.records() == [make_record(i) for i in range(7, 10)]
        assert reopened.append(make_record(10)) is True
        reopened.close()
        assert ScanTimeline.read_records(path) == [make_record(i) for i in range(7, 11)]

    def test_disk_fault_degrades_and_next_append_truncates_tail(self, tmp_path):
        path = str(tmp_path / "timeline.log")
        registry = MetricsRegistry()
        timeline = ScanTimeline.open(path, metrics=registry)
        assert timeline.append(make_record(0)) is True
        # Fault the fsync: the append part-writes, marks the tail dirty,
        # degrades to memory-only for that record.
        timeline.fs = FaultyFs(ops=("fsync",))
        assert timeline.append(make_record(1)) is False
        assert registry.total("krr_tpu_timeline_append_failures_total") == 1.0
        assert len(timeline.records()) == 2  # memory ring kept it
        # Healed: the next append truncates the torn bytes first, so the
        # durable file holds records 0 and 2 — both cleanly framed.
        timeline.fs = type(timeline.fs).__mro__[1]()  # plain FsOps
        assert timeline.append(make_record(2)) is True
        timeline.close()
        assert ScanTimeline.read_records(path) == [make_record(0), make_record(2)]

    def test_failed_retention_compaction_degrades_and_retries(self, tmp_path):
        """A disk fault during the retention rewrite must not undo the
        append's durable verdict or escape to the caller — bookkeeping
        re-derives from the file and a later (healed) append compacts."""
        from krr_tpu.core.streaming import FsOps

        path = str(tmp_path / "timeline.log")
        timeline = ScanTimeline.open(path, retain_records=2)
        for i in range(4):
            assert timeline.append(make_record(i)) is True
        # The 5th append crosses 2*retain; the compaction's atomic rewrite
        # faults at its rename (appends don't use replace, so the record
        # itself commits durably first).
        timeline.fs = FaultyFs(ops=("replace",))
        assert timeline.append(make_record(4)) is True
        assert ScanTimeline.read_records(path) == [make_record(i) for i in range(5)]
        # Healed: the next append retries the compaction successfully.
        timeline.fs = FsOps()
        assert timeline.append(make_record(5)) is True
        timeline.close()
        assert ScanTimeline.read_records(path) == [make_record(4), make_record(5)]

    def test_read_records_never_writes(self, tmp_path):
        path = str(tmp_path / "timeline.log")
        timeline = ScanTimeline.open(path)
        timeline.append(make_record(0))
        timeline.close()
        with open(path, "ab") as f:
            f.write(b"torn-tail-bytes")
        before = open(path, "rb").read()
        assert ScanTimeline.read_records(path) == [make_record(0)]
        assert open(path, "rb").read() == before  # untouched, torn tail included

    def test_memory_only_recorder(self):
        timeline = ScanTimeline.open(None, retain_records=3)
        for i in range(5):
            assert timeline.append(make_record(i)) is False
        assert [r["scan_id"] for r in timeline.records()] == ["scan-2", "scan-3", "scan-4"]
        assert timeline.records(2) == [make_record(3), make_record(4)]
        assert timeline.nbytes == 0


class TestBuildScanRecord:
    def test_distills_profile_and_stats(self):
        from krr_tpu.obs.profile import profile_trace

        tracer = Tracer(ring_scans=4)
        with tracer.span("scan", kind="serve"):
            with tracer.span("fetch", namespace="default"):
                pass
            with tracer.span("compute", rows=2):
                pass
        report = profile_trace(tracer.traces()[-1])
        registry = MetricsRegistry()
        registry.set("krr_tpu_prom_inflight_limit", 24, cluster="fake")
        stats = {
            "scan_id": report["scan_id"],
            "kind": "delta",
            "window_start": 100.0,
            "window_end": 400.0,
            "objects": 2,
            "failed_rows": 1,
            "backfilled": 0,
            "stale": 1,
            "publish_changed": 2,
            "publish_suppressed": 3,
            "persist_seconds": 0.5,
            "persist_bytes": 4096,
            "epoch": 7,
        }
        record = build_scan_record(
            report, stats, metrics=registry,
            plan_delta={"coalesced": 2, "sharded": 1, "downsampled": 4},
        )
        assert record["kind"] == "delta" and record["ts"] == 400.0
        assert record["window_seconds"] == 300.0
        assert set(record["categories"]) == set(report["categories"])
        assert record["rows"] == 2 and record["failed_rows"] == 1
        assert record["publish"] == {"changed": 2, "suppressed": 3}
        assert record["persist"]["epoch"] == 7 and record["persist"]["bytes"] == 4096
        assert record["plan"] == {
            "coalesced": 2, "sharded": 1, "downsampled": 4, "inflight_limit": 24.0,
        }
        # No compressed response contributed: the ratio must be absent, not
        # a fabricated identity 1.0.
        assert record["wire_compression_ratio"] is None
        assert record["encodings"] == {}
        # Records must be JSON-serializable as-is (the timeline frames JSON).
        json.dumps(record)

    def test_compression_fields(self):
        """A tick whose queries negotiated gzip carries the per-tick ratio
        (decoded ÷ wire) and the encoding census."""
        tracer = Tracer(ring_scans=4)
        with tracer.span("scan", kind="serve"):
            span = tracer.start_span(
                "prom_query", route="streamed", status="ok", retries=0,
            )
            span.set(bytes=1_000_000, decoded_bytes=10_000_000, encoding="gzip")
            tracer.finish_span(span)
        from krr_tpu.obs.profile import profile_trace

        report = profile_trace(tracer.traces()[-1])
        record = build_scan_record(report, {"kind": "delta", "window_end": 50.0})
        assert record["wire_bytes"] == 1_000_000
        assert record["decoded_bytes"] == 10_000_000
        assert record["wire_compression_ratio"] == 10.0
        assert record["encodings"] == {"gzip": 1}
        json.dumps(record)

    def test_missing_profile_degrades_to_zeroes(self):
        record = build_scan_record(None, {"kind": "full", "window_end": 50.0})
        assert record["wall"] == 0.0 and record["categories"] == {}
        json.dumps(record)


# ------------------------------------------------------------------ sentinel
class TestRegressionSentinel:
    def _warm(self, sentinel: RegressionSentinel, n: int = 10, rng=None) -> int:
        rng = rng or np.random.default_rng(0)
        for i in range(n):
            jitter = {
                k: v * float(1.0 + rng.normal(0, 0.03)) for k, v in BASE_CATEGORIES.items()
            }
            verdict = sentinel.observe(make_record(i, categories=jitter), fire=False)
            assert verdict["status"] in ("warming", "nominal")
        return n

    def test_warmup_gates_verdicts(self):
        sentinel = RegressionSentinel(warmup_scans=4)
        for i in range(4):
            assert sentinel.observe(make_record(i), fire=False)["status"] == "warming"
        assert sentinel.classified_scans == 0
        assert sentinel.observe(make_record(4), fire=False)["status"] == "nominal"
        assert sentinel.classified_scans == 1

    def test_fetch_transport_regression_attributed_with_phase_detail(self):
        registry = MetricsRegistry()
        sentinel = RegressionSentinel(warmup_scans=4, metrics=registry)
        n = self._warm(sentinel, 10)
        bad = make_record(
            n,
            categories={"fetch_transport": 1.8},
            phases={"ttfb": 1.6},
        )
        verdict = sentinel.observe(bad)
        assert verdict["status"] == "regressed"
        assert verdict["dominant"] == "fetch_transport"
        assert verdict["sigma"] >= 3.0
        assert "ttfb-dominated" in verdict["suspect"]
        assert "Prometheus" in verdict["suspect"]
        # Fired: the gauge carries the sigmas, the counter the dominant.
        assert registry.value("krr_tpu_scan_regression", category="fetch_transport") > 0
        assert (
            registry.value("krr_tpu_scan_regressions_total", category="fetch_transport")
            == 1.0
        )
        # A nominal scan right after zeroes the gauge.
        sentinel.observe(make_record(n + 1))
        assert registry.value("krr_tpu_scan_regression", category="fetch_transport") == 0.0

    def test_compute_regression_attributed(self):
        sentinel = RegressionSentinel(warmup_scans=4)
        n = self._warm(sentinel, 10)
        verdict = sentinel.observe(make_record(n, categories={"compute": 1.2}), fire=False)
        assert verdict["status"] == "regressed" and verdict["dominant"] == "compute"
        assert "compute" in verdict["suspect"]

    def test_clean_noisy_series_stays_nominal(self):
        sentinel = RegressionSentinel(warmup_scans=8)
        rng = np.random.default_rng(7)
        verdicts = []
        for i in range(60):
            jitter = {
                k: v * float(1.0 + rng.normal(0, 0.05)) for k, v in BASE_CATEGORIES.items()
            }
            verdicts.append(sentinel.observe(make_record(i, categories=jitter), fire=False))
        assert sum(1 for v in verdicts if v["status"] == "regressed") == 0

    def test_regressed_scans_do_not_poison_the_baseline(self):
        sentinel = RegressionSentinel(warmup_scans=4)
        n = self._warm(sentinel, 10)
        for i in range(5):  # a sustained regression keeps firing...
            verdict = sentinel.observe(
                make_record(n + i, categories={"fetch_transport": 1.8}), fire=False
            )
            assert verdict["status"] == "regressed"
        # ...and the recovered regime is still nominal (the elevated values
        # never folded into the baseline).
        verdict = sentinel.observe(make_record(n + 5), fire=False)
        assert verdict["status"] == "nominal"

    def test_sustained_regime_rebases_after_a_baseline_window(self):
        sentinel = RegressionSentinel(warmup_scans=4, baseline_scans=6)
        n = self._warm(sentinel, 8)
        statuses = [
            sentinel.observe(
                make_record(n + i, categories={"fetch_transport": 1.8}), fire=False
            )["status"]
            for i in range(8)
        ]
        # Every scan of the acceptance window pages; the moment the streak
        # fills a whole baseline window the baseline is REPLACED with the
        # new regime, so the very next elevated scan is nominal — not
        # baseline_scans² ticks of median creep.
        assert statuses[:6] == ["regressed"] * 6
        assert statuses[6:] == ["nominal"] * 2

    def test_baselines_are_per_kind(self):
        sentinel = RegressionSentinel(warmup_scans=3)
        self._warm(sentinel, 6)  # delta regime warmed
        # A FULL scan costs 10x a delta: it must not be judged against the
        # delta baseline — its own kind is still warming.
        full = make_record(
            100, kind="full", categories={k: v * 10 for k, v in BASE_CATEGORIES.items()}
        )
        assert sentinel.observe(full, fire=False)["status"] == "warming"

    def test_seed_replays_and_survives_restart(self):
        records = [make_record(i) for i in range(10)]
        first = RegressionSentinel(warmup_scans=4)
        for record in records:
            first.observe(record, fire=False)
        assert first.warmed("delta")
        # "Restart": a fresh sentinel seeded from the recovered timeline is
        # warm immediately — no re-warm-up window after every restart.
        reborn = RegressionSentinel(warmup_scans=4)
        assert reborn.seed(records) == 10
        assert reborn.warmed("delta")
        assert reborn.classified_scans == 0  # live counters start fresh
        verdict = reborn.observe(make_record(11, categories={"compute": 1.5}), fire=False)
        assert verdict["status"] == "regressed" and verdict["dominant"] == "compute"

    def test_slo_objective_counts_regressions(self):
        from krr_tpu.obs.health import Objective, SloEngine

        sentinel = RegressionSentinel(warmup_scans=3)
        engine = SloEngine([], clock=lambda: 0.0)
        engine.add_objective(
            Objective(
                name="scan_regressions",
                description="test",
                budget=0.1,
                sample=lambda: (
                    float(sentinel.regressed_scans),
                    float(sentinel.classified_scans),
                ),
            )
        )
        self._warm(sentinel, 6)
        sentinel.observe(make_record(50, categories={"fold": 2.0}), fire=False)
        engine.evaluate(now=1.0)
        status = engine.status(now=1.0)
        obj = status["objectives"][0]
        assert obj["events"]["bad"] == 1.0
        assert obj["events"]["total"] == float(sentinel.classified_scans)

    def test_trend_report_and_text_render(self):
        records = [make_record(i) for i in range(12)]
        records.append(make_record(12, categories={"fetch_transport": 2.0}))
        report = trend_report(records, warmup_scans=4)
        assert report["scans"] == 13 and report["regressed"] == 1
        assert report["regressions"][0]["dominant"] == "fetch_transport"
        text = render_trend_text(report, records)
        assert "REGRESSED" in text and "fetch_transport" in text
        assert "baseline[delta]" in text


# ---------------------------------------------------------------- HTTP routes
class TestDebugTimelineRoute:
    def _app(self, timeline=None, sentinel=None, tracer=None):
        from krr_tpu.server.app import HttpApp
        from krr_tpu.server.state import ServerState
        from krr_tpu.utils.logging import NULL_LOGGER

        class FakeStore:
            keys: list = []

        state = ServerState(FakeStore())
        state.timeline = timeline
        state.sentinel = sentinel
        return HttpApp(state, NULL_LOGGER, tracer=tracer or Tracer(ring_scans=2))

    def test_404_without_a_timeline(self):
        status, _ct, body = asyncio.run(self._app().route("GET", "/debug/timeline", {}))
        assert status == 404 and b"no scan timeline" in body

    def test_json_records_and_trend(self):
        timeline = ScanTimeline.open(None)
        for i in range(6):
            timeline.append(make_record(i))
        sentinel = RegressionSentinel(warmup_scans=3)
        app = self._app(timeline, sentinel)
        status, content_type, body = asyncio.run(app.route("GET", "/debug/timeline", {}))
        assert status == 200 and content_type == "application/json"
        payload = json.loads(body)
        assert len(payload["records"]) == 6
        assert payload["trend"]["scans"] == 6
        assert payload["live"] is not None
        # n limits the records (and the per-record verdict list), not the
        # trend's replay coverage.
        status, _ct, body = asyncio.run(app.route("GET", "/debug/timeline", {"n": ["2"]}))
        payload = json.loads(body)
        assert len(payload["records"]) == 2 and payload["trend"]["scans"] == 6
        assert len(payload["trend"]["verdicts"]) == 2

    def test_text_format(self):
        timeline = ScanTimeline.open(None)
        for i in range(4):
            timeline.append(make_record(i))
        app = self._app(timeline)
        status, content_type, body = asyncio.run(
            app.route("GET", "/debug/timeline", {"format": ["text"]})
        )
        assert status == 200 and content_type.startswith("text/plain")
        assert b"scan timeline" in body
        status, _ct, _body = asyncio.run(
            app.route("GET", "/debug/timeline", {"format": ["xml"]})
        )
        assert status == 400

    @pytest.mark.parametrize("path", ["/debug/trace", "/debug/profile", "/debug/timeline"])
    @pytest.mark.parametrize("bad", ["x", "-1", "1.5", ""])
    def test_shared_n_validation_rejects_with_400_json(self, path, bad):
        app = self._app(ScanTimeline.open(None))
        status, content_type, body = asyncio.run(app.route("GET", path, {"n": [bad]}))
        assert status == 400, f"{path} n={bad!r}"
        assert content_type == "application/json"
        assert "error" in json.loads(body)


class TestStatuszTrendSection:
    def test_trend_rides_statusz(self):
        from krr_tpu.obs.health import SloEngine
        from krr_tpu.server.app import HttpApp
        from krr_tpu.server.state import ServerState
        from krr_tpu.utils.logging import NULL_LOGGER

        class FakeStore:
            keys: list = []

        state = ServerState(FakeStore())
        state.slo = SloEngine([], clock=lambda: 0.0)
        sentinel = RegressionSentinel(warmup_scans=3)
        for i in range(6):
            sentinel.observe(make_record(i), fire=False)
        state.sentinel = sentinel
        app = HttpApp(state, NULL_LOGGER)
        status, _ct, body = asyncio.run(app.route("GET", "/statusz", {}))
        assert status == 200
        payload = json.loads(body)
        assert payload["trend"]["baselines"]["delta"]["warmed"] is True
        assert payload["trend"]["classified_scans"] == sentinel.classified_scans
        status, _ct, body = asyncio.run(app.route("GET", "/statusz", {"format": ["text"]}))
        assert b"trend (regression sentinel)" in body


class TestTrendDumpArtifact:
    def test_sigusr2_dump_gains_the_trend_artifact(self, tmp_path):
        from krr_tpu.obs.dump import debug_dump

        timeline = ScanTimeline.open(None)
        for i in range(3):
            timeline.append(make_record(i))
        tracer = Tracer(ring_scans=2)
        with tracer.span("scan"):
            pass
        paths = debug_dump(
            tracer,
            MetricsRegistry(),
            trace_target=str(tmp_path / "trace.json"),
            metrics_target=str(tmp_path / "metrics.prom"),
            timeline=timeline,
            sentinel=RegressionSentinel(),
        )
        assert len(paths) == 4
        trend = json.load(open(paths[3]))
        assert len(trend["records"]) == 3 and trend["trend"]["scans"] == 3
        # Without a timeline (one-shot scans) the dump keeps its 3 artifacts.
        assert (
            len(
                debug_dump(
                    tracer,
                    MetricsRegistry(),
                    trace_target=str(tmp_path / "trace.json"),
                    metrics_target=str(tmp_path / "metrics.prom"),
                )
            )
            == 3
        )


# ------------------------------------------------------------------- the CLI
class TestAnalyzeTrend:
    def _invoke(self, args):
        from click.testing import CliRunner

        from krr_tpu.main import _make_analyze_command

        return CliRunner().invoke(_make_analyze_command(), args)

    def test_trend_over_a_timeline_file(self, tmp_path):
        path = str(tmp_path / "timeline.log")
        timeline = ScanTimeline.open(path)
        for i in range(10):
            timeline.append(make_record(i))
        timeline.append(make_record(10, categories={"fetch_transport": 2.0}))
        timeline.close()
        result = self._invoke(["--timeline", path])
        assert result.exit_code == 0, result.output
        assert "REGRESSED" in result.output and "fetch_transport" in result.output
        result = self._invoke(["--trend", "--timeline", path, "--format", "json"])
        assert result.exit_code == 0
        payload = json.loads(result.output)
        assert payload["trend"]["regressed"] == 1

    def test_n_limits_rendered_records_not_the_replay(self, tmp_path):
        """-n must slice the DISPLAY, not the classification input: a
        truncated replay would re-warm from scratch and erase verdicts the
        server issued over the full baseline."""
        path = str(tmp_path / "timeline.log")
        timeline = ScanTimeline.open(path)
        for i in range(10):
            timeline.append(make_record(i))
        timeline.append(make_record(10, categories={"fetch_transport": 2.0}))
        timeline.close()
        result = self._invoke(["--timeline", path, "-n", "2", "--format", "json"])
        assert result.exit_code == 0, result.output
        payload = json.loads(result.output)
        assert len(payload["records"]) == 2
        assert payload["trend"]["scans"] == 11 and payload["trend"]["regressed"] == 1

    def test_empty_timeline_is_benign(self, tmp_path):
        path = str(tmp_path / "timeline.log")
        ScanTimeline.open(path).close()
        result = self._invoke(["--timeline", path])
        assert result.exit_code == 0
        assert "no completed scans" in result.output

    def test_trend_refuses_trace_input(self, tmp_path):
        result = self._invoke(["--trend", "--trace", "x"])
        assert result.exit_code != 0
        result = self._invoke(["--trend"])
        assert result.exit_code != 0

    def test_url_with_empty_ring_exits_clean(self):
        """The satellite: `analyze --url` against a fresh serve (no
        completed ticks, empty trace ring) prints a clear message and exits
        0 instead of an empty report + error."""
        import http.server
        import threading

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                body = json.dumps(
                    {"records": []}
                    if self.path.startswith("/debug/timeline")
                    else {"traceEvents": [], "displayTimeUnit": "ms"}
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        server = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            url = f"http://127.0.0.1:{server.server_port}"
            result = self._invoke(["--url", url])
            assert result.exit_code == 0, result.output
            assert "no completed scans yet" in result.output
            result = self._invoke(["--trend", "--url", url])
            assert result.exit_code == 0, result.output
            assert "no completed scans" in result.output
        finally:
            server.shutdown()
            thread.join(timeout=5)
