"""The regression sentinel: baseline-banded per-scan trend classification.

The timeline (`krr_tpu.obs.timeline`) answers "what did scan N cost, by
category"; this module answers the question operators actually have:
"is scan N NORMAL for this fleet?". It maintains robust rolling baselines
— per-category median/MAD bands over the recorded timeline, kept per scan
KIND (a full-window scan and a delta tick live in different cost regimes)
— and classifies every completed scan as ``nominal`` or ``regressed``,
attributing a regression to the dominant deviating category and naming the
suspect layer (e.g. ``fetch_transport +2.1σ, ttfb-dominated → Prometheus
side``).

Band math:

* For each monitored series (the profile categories plus the whole wall),
  the baseline holds the last ``baseline_scans`` NOMINAL values. The band
  unit is ``max(1.4826·MAD, rel_floor·median, abs_floor)`` — the MAD term
  adapts to the fleet's real jitter, the relative and absolute floors keep
  a near-constant series (MAD ≈ 0) from flagging microsecond noise as an
  infinite-sigma regression.
* A category regresses when its value exceeds ``median + sigma·unit``. The
  scan's verdict is ``regressed`` when any category does; the DOMINANT
  category is the one with the largest excess seconds over its median —
  the one that actually added wall — and for ``fetch_transport`` the
  transport-phase bands name which phase dominates the deviation
  (ttfb vs connect vs body_read), which is the Prometheus-side vs
  network vs volume distinction.
* Warm-up gating: no verdicts until ``warmup_scans`` nominal records of
  the scan's kind have been observed — a cold server must not page on its
  first tick.
* Poison-proofing: regressed scans do NOT fold into the baseline, so a
  regression can't normalize itself away tick by tick. A sustained new
  regime is still accepted: after ``baseline_scans`` CONSECUTIVE regressed
  verdicts of one kind the sentinel rebases (folds the record, logs the
  acceptance) instead of alerting forever on a level shift the operator
  has evidently accepted.

Verdicts fire four ways: the ``krr_tpu_scan_regression{category}`` gauge
(deviation sigmas while regressed, 0 while nominal) and the
``krr_tpu_scan_regressions_total{category}`` counter, one structured log
event, the ``/statusz`` trend section, and (``--sentinel-slo``) an SLO
objective whose bad events are regressed scans. Everything here is pure
host arithmetic over the record dicts — the serve scheduler, ``krr-tpu
analyze --trend``, ``GET /debug/timeline``, and the bench sentinel leg all
drive the SAME code.
"""

from __future__ import annotations

import statistics
import threading
from collections import deque
from typing import Optional

from krr_tpu.obs.profile import CATEGORIES

#: Monitored categories — the profile partition minus ``idle`` (idle wall is
#: the scheduler waiting, not a cost regression), the whole wall, and the
#: tick's wire megabytes (``wire_mb`` — the one non-seconds series: a
#: silent fallback to identity transport multiplies wire bytes by the
#: compression ratio while every timing band may stay green, and it must
#: page as a trend verdict, not a mystery slowdown later).
#: ``read_p99_ms`` rides along the same way: the read path's per-tick p99
#: (milliseconds — a value band like wire_mb, not a scan-seconds band), so
#: a cache-hit-rate collapse or render-pool saturation pages as a trend
#: verdict instead of a mystery latency complaint from clients.
#: The four freshness-lineage hops (federation mode): each series is the
#: LATENCY OF ONE HOP of the epoch's end-to-end lineage chain (newest
#: sample → shard fold → aggregator apply → publish → replica install),
#: so a freshness regression pages with the guilty hop named instead of a
#: generic "replica lag regressed". Value bands (seconds of pipeline AGE,
#: not seconds of scan wall): a 300s delivery stall must not out-rank a
#: genuine compute regression in the dominant pool.
_FRESHNESS_HOPS = (
    "freshness_fold",
    "freshness_apply",
    "freshness_publish",
    "freshness_install",
)

MONITORED = (
    tuple(c for c in CATEGORIES if c != "idle")
    + ("wall", "wire_mb", "read_p99_ms")
    + _FRESHNESS_HOPS
)

#: Value-band series (not scan-seconds): excluded from the seconds-ranked
#: dominant pool, and rendered/reported in their own units.
_VALUE_BANDS = {"wire_mb": "MB", "read_p99_ms": "ms"}
_VALUE_BANDS.update({hop: "s" for hop in _FRESHNESS_HOPS})

#: Transport phases whose bands refine a fetch_transport attribution.
_PHASE_DETAIL = ("connect", "request_write", "ttfb", "body_read", "queue_wait")

#: category → the layer an operator should suspect first.
SUSPECT_LAYERS = {
    "fetch_transport": "Prometheus side / network transport",
    "fetch_decode": "response decode / native sink (client CPU)",
    "fetch_backoff": "retry backoff → flaky Prometheus backend",
    "fetch_other": "fetch routing / client-side query handling",
    "fold": "host fold stage (digest merge)",
    "compute": "device compute / recommendation stage",
    "discover": "Kubernetes inventory (apiserver)",
    "publish": "render + publish stage",
    "other": "scheduler / uncategorized host work",
    "wall": "whole-scan wall (no single dominant category)",
    "wire_mb": (
        "wire bytes up at steady timings → compression fell back to identity "
        "(a proxy stripping Accept-Encoding?) or response volume grew — "
        "check the record's encodings and downsample engagement"
    ),
    "read_p99_ms": (
        "read-path p99 up → response-cache hit rate collapsed (epoch churn? "
        "filter-cardinality evictions?) or the render pool saturated — "
        "check the record's readpath hits/misses/shed split"
    ),
    "freshness_fold": (
        "sample→fold hop up → the SHARD side: its scan cadence slipped or "
        "its fetch/fold leg slowed — check the shard's scan duration and "
        "consecutive-failure counters"
    ),
    "freshness_apply": (
        "fold→apply hop up → shard→aggregator DELIVERY: unacked backlog, "
        "reconnect churn, or aggregator backpressure — check "
        "krr_tpu_federation_unacked_records and the aggregate tick cadence"
    ),
    "freshness_publish": (
        "apply→publish hop up → the AGGREGATOR's compute/render/persist "
        "stage between replay and snapshot swap — check the tick's "
        "compute/persist seconds"
    ),
    "freshness_install": (
        "publish→install hop up → the REPLICA leg: feed broadcast, frame "
        "decode, or the install swap slowed (replica lag regressed) — "
        "check krr_tpu_replica_feed_lag_seconds and /fleet epoch lag"
    ),
}

#: phase → the refinement appended to a fetch_transport attribution.
_PHASE_SUSPECTS = {
    "ttfb": "ttfb-dominated → Prometheus side (server think time)",
    "connect": "connect-dominated → network / connection churn",
    "request_write": "request-write-dominated → uplink / proxy",
    "body_read": "body-read-dominated → response volume / bandwidth",
    "queue_wait": "queue-wait-dominated → client concurrency limit",
}


class _Baseline:
    """Rolling nominal history for one (kind, series) pair."""

    __slots__ = ("values",)

    def __init__(self, maxlen: int) -> None:
        self.values: "deque[float]" = deque(maxlen=maxlen)

    def band(self, rel_floor: float, abs_floor: float) -> "tuple[float, float]":
        """(median, unit) — unit is the 1σ band width."""
        values = list(self.values)
        med = statistics.median(values)
        mad = statistics.median(abs(v - med) for v in values)
        return med, max(1.4826 * mad, rel_floor * med, abs_floor)


class RegressionSentinel:
    """Classifies timeline records against rolling median/MAD baselines."""

    def __init__(
        self,
        *,
        warmup_scans: int = 8,
        baseline_scans: int = 64,
        sigma: float = 3.0,
        rel_floor: float = 0.10,
        abs_floor_seconds: float = 0.05,
        metrics=None,
        logger=None,
    ) -> None:
        self.warmup_scans = max(2, int(warmup_scans))
        self.baseline_scans = max(self.warmup_scans, int(baseline_scans))
        self.sigma = float(sigma)
        self.rel_floor = float(rel_floor)
        self.abs_floor_seconds = float(abs_floor_seconds)
        self.metrics = metrics
        self.logger = logger
        #: kind -> series name -> baseline.
        self._baselines: "dict[str, dict[str, _Baseline]]" = {}
        #: kind -> nominal records folded (the warm-up gate's counter).
        self._observed: "dict[str, int]" = {}
        #: kind -> consecutive regressed verdicts (regime-acceptance rebase).
        self._regressed_streak: "dict[str, int]" = {}
        #: kind -> the streak's observed values (newest ``baseline_scans``),
        #: so acceptance can REPLACE the baseline with the new regime in one
        #: step — folding a single value per window would take
        #: ~baseline_scans² ticks to actually move the median.
        self._streak_values: "dict[str, list[dict]]" = {}
        #: Cumulative verdicts — the optional SLO objective's event counts.
        self.classified_scans = 0
        self.regressed_scans = 0
        self.last_verdict: Optional[dict] = None
        #: Serve classifies on the event loop while ``/debug/timeline``
        #: renders and SIGUSR2 dumps call :meth:`status` from worker
        #: threads — the baseline deques must not mutate mid-iteration.
        #: Reentrant: :meth:`seed` replays through :meth:`observe`.
        self._lock = threading.RLock()

    # ------------------------------------------------------------ observation
    @staticmethod
    def _series_of(record: dict) -> "dict[str, float]":
        categories = record.get("categories") or {}
        values = {c: float(categories.get(c, 0.0)) for c in CATEGORIES if c != "idle"}
        values["wall"] = float(record.get("wall", 0.0))
        # Wire megabytes — a value band, not a timing band (its "excess" is
        # MB, not seconds). A record WITHOUT wire bytes (pre-compression
        # timeline files, fake-source deployments) contributes NO sample:
        # folding 0.0 would seed an all-zero baseline whose floor-width
        # band pages a guaranteed false "compression fell back" verdict on
        # the first real post-upgrade scan — the series must instead warm
        # up on its own real samples (the per-series warm-up gate in
        # `_observe` holds verdicts until it has them).
        wire_bytes = record.get("wire_bytes") or 0
        if wire_bytes:
            values["wire_mb"] = float(wire_bytes) / 1e6
        # Read-path p99 — same no-sample-when-absent discipline as wire_mb:
        # a quiet tick (no /recommendations traffic) or a pre-read-path
        # record contributes nothing, so the band warms only on ticks that
        # actually served reads.
        readpath = record.get("readpath") or {}
        if readpath.get("requests") and readpath.get("p99_ms") is not None:
            values["read_p99_ms"] = float(readpath["p99_ms"])
        # Freshness lineage hops — no-sample-when-absent like wire_mb: a
        # non-federation record (or lineage off) contributes nothing, and
        # the install hop only samples on ticks with a replica-acked epoch
        # (acks trail the publishing tick by design).
        lineage = record.get("lineage") or {}
        newest = lineage.get("newest_sample_ts")
        fold_ts = lineage.get("fold_ts")
        apply_ts = lineage.get("apply_ts")
        publish_ts = lineage.get("publish_ts")
        if newest is not None and fold_ts is not None:
            values["freshness_fold"] = max(0.0, float(fold_ts) - float(newest))
            if apply_ts is not None:
                values["freshness_apply"] = max(0.0, float(apply_ts) - float(fold_ts))
                if publish_ts is not None:
                    values["freshness_publish"] = max(
                        0.0, float(publish_ts) - float(apply_ts)
                    )
        install = lineage.get("install") or {}
        if (
            install.get("install_ts") is not None
            and install.get("publish_ts") is not None
        ):
            values["freshness_install"] = max(
                0.0, float(install["install_ts"]) - float(install["publish_ts"])
            )
        for phase, seconds in (record.get("phases") or {}).items():
            if phase in _PHASE_DETAIL:
                values[f"phase_{phase}"] = float(seconds)
        return values

    def _fold(self, kind: str, values: "dict[str, float]") -> None:
        baselines = self._baselines.setdefault(kind, {})
        for name, value in values.items():
            baseline = baselines.get(name)
            if baseline is None:
                baseline = baselines[name] = _Baseline(self.baseline_scans)
            baseline.values.append(value)
        self._observed[kind] = self._observed.get(kind, 0) + 1

    def observe(self, record: dict, *, fire: bool = True) -> dict:
        """Classify one record and (unless warming) update the verdict
        counters; ``fire=False`` suppresses metrics/log side effects — the
        seed replay and offline ``--trend`` reports ride the same path."""
        with self._lock:
            return self._observe(record, fire=fire)

    def _observe(self, record: dict, *, fire: bool) -> dict:
        kind = str(record.get("kind", "delta"))
        values = self._series_of(record)
        baselines = self._baselines.get(kind, {})
        warmed = self._observed.get(kind, 0) >= self.warmup_scans
        verdict: dict = {
            "ts": record.get("ts"),
            "scan_id": record.get("scan_id"),
            "kind": kind,
            "status": "warming" if not warmed else "nominal",
            "categories": {},
        }
        if not warmed:
            self._fold(kind, values)
            self.last_verdict = verdict
            if fire:
                self._fire(verdict)
            return verdict

        deviations: "dict[str, dict]" = {}
        for name, value in values.items():
            baseline = baselines.get(name)
            if baseline is None or len(baseline.values) < self.warmup_scans:
                continue
            median, unit = baseline.band(self.rel_floor, self.abs_floor_seconds)
            sigmas = (value - median) / unit if unit > 0 else 0.0
            deviations[name] = {
                "value": round(value, 6),
                "median": round(median, 6),
                "sigma": round(sigmas, 2),
                "regressed": sigmas >= self.sigma,
            }
        verdict["categories"] = {
            name: deviations[name] for name in deviations if not name.startswith("phase_")
        }
        regressed = [
            name
            for name, d in deviations.items()
            if d["regressed"] and not name.startswith("phase_") and name != "wall"
        ]
        self.classified_scans += 1
        if regressed:
            # Dominant = the category that ADDED the most wall, not the one
            # with the tightest band: attribution must name where the
            # seconds went. Value bands (wire_mb in megabytes, read_p99_ms
            # in milliseconds) — ranked against seconds their raw excess
            # would win almost every co-occurring regression at fleet
            # scale, so they only become dominant when no timing category
            # regressed alongside them.
            timing = [name for name in regressed if name not in _VALUE_BANDS]
            pool = timing or regressed
            dominant = max(
                pool, key=lambda name: deviations[name]["value"] - deviations[name]["median"]
            )
            detail = self._phase_detail(dominant, deviations)
            suspect = SUSPECT_LAYERS.get(dominant, dominant)
            if detail:
                suspect = f"{detail} ({suspect})"
            verdict.update(
                status="regressed",
                dominant=dominant,
                sigma=deviations[dominant]["sigma"],
                # In the dominant series' unit (see excess_unit) — seconds
                # for every timing category, megabytes for wire_mb.
                excess_seconds=round(
                    deviations[dominant]["value"] - deviations[dominant]["median"], 6
                ),
                excess_unit=_VALUE_BANDS.get(dominant, "s"),
                regressed=regressed,
                suspect=suspect,
            )
            self.regressed_scans += 1
            streak = self._regressed_streak.get(kind, 0) + 1
            buffer = self._streak_values.setdefault(kind, [])
            buffer.append(values)
            if len(buffer) > self.baseline_scans:
                del buffer[: len(buffer) - self.baseline_scans]
            if streak >= self.baseline_scans:
                # Regime acceptance: a level shift that held for a whole
                # baseline window is the new normal — REPLACE the baseline
                # with the streak itself, so the very next scan of the new
                # regime classifies nominal instead of paging on for
                # baseline_scans² ticks while single folds creep the median.
                self._baselines.pop(kind, None)
                for streak_values in buffer:
                    self._fold(kind, streak_values)
                buffer.clear()
                self._regressed_streak[kind] = 0
                if self.logger is not None and fire:
                    self.logger.info(
                        f"sentinel: accepting new {kind}-scan cost regime after "
                        f"{streak} consecutive regressed scans (rebasing baselines)"
                    )
            else:
                self._regressed_streak[kind] = streak
        else:
            # Only wall (or nothing) deviated: classify nominal — a wall
            # deviation with no category behind it is sweep noise.
            self._regressed_streak[kind] = 0
            self._streak_values.pop(kind, None)
            self._fold(kind, values)
        self.last_verdict = verdict
        if fire:
            self._fire(verdict)
        return verdict

    def _phase_detail(self, dominant: str, deviations: dict) -> Optional[str]:
        if dominant != "fetch_transport":
            return None
        best, best_excess = None, 0.0
        for phase in _PHASE_DETAIL:
            d = deviations.get(f"phase_{phase}")
            if d is None:
                continue
            excess = d["value"] - d["median"]
            if d["sigma"] >= self.sigma and excess > best_excess:
                best, best_excess = phase, excess
        return _PHASE_SUSPECTS.get(best) if best else None

    def _fire(self, verdict: dict) -> None:
        if self.metrics is not None:
            for name, d in verdict.get("categories", {}).items():
                self.metrics.set(
                    "krr_tpu_scan_regression",
                    d["sigma"] if d["regressed"] else 0.0,
                    category=name,
                )
            if verdict["status"] == "regressed":
                self.metrics.inc(
                    "krr_tpu_scan_regressions_total", category=verdict["dominant"]
                )
        if self.logger is not None and verdict["status"] == "regressed":
            self.logger.warning(
                f"scan regression: {verdict.get('scan_id') or 'scan'} "
                f"[{verdict['kind']}] {verdict['dominant']} "
                f"+{verdict['sigma']:.1f}σ (+{verdict['excess_seconds']:.3f}"
                f"{verdict.get('excess_unit', 's')} "
                f"over baseline) → {verdict['suspect']}"
            )

    def seed(self, records: "list[dict]") -> int:
        """Replay recovered timeline records WITHOUT side effects, so the
        baselines (and warm-up state) survive a restart exactly as the
        durable timeline does. Returns the number of records replayed."""
        with self._lock:
            for record in records:
                self._observe(record, fire=False)
            # A seeded sentinel starts its live verdict stream fresh: the
            # SLO objective must count this process's scans, not replayed
            # history.
            self.classified_scans = 0
            self.regressed_scans = 0
        return len(records)

    # ----------------------------------------------------------------- status
    def warmed(self, kind: str = "delta") -> bool:
        return self._observed.get(kind, 0) >= self.warmup_scans

    def status(self) -> dict:
        """The ``/statusz`` trend section: warm-up posture, current bands,
        and the last verdict. Thread-safe (see ``_lock``)."""
        with self._lock:
            return self._status()

    def _status(self) -> dict:
        baselines = {}
        for kind, series in self._baselines.items():
            rendered = {}
            for name in MONITORED:
                baseline = series.get(name)
                if baseline is None or len(baseline.values) < 2:
                    continue
                median, unit = baseline.band(self.rel_floor, self.abs_floor_seconds)
                rendered[name] = {
                    "median": round(median, 6),
                    "band": round(unit, 6),
                    "samples": len(baseline.values),
                }
            baselines[kind] = {
                "warmed": self.warmed(kind),
                "observed": self._observed.get(kind, 0),
                "series": rendered,
            }
        return {
            "warmup_scans": self.warmup_scans,
            "baseline_scans": self.baseline_scans,
            "sigma": self.sigma,
            "classified_scans": self.classified_scans,
            "regressed_scans": self.regressed_scans,
            "baselines": baselines,
            "last_verdict": self.last_verdict,
        }


def sentinel_knobs(sentinel: "Optional[RegressionSentinel]") -> dict:
    """A live sentinel's band knobs as :func:`trend_report` kwargs, so an
    offline replay classifies exactly as the serve-side sentinel does
    (defaults when no sentinel is configured)."""
    if sentinel is None:
        return {}
    return dict(
        warmup_scans=sentinel.warmup_scans,
        baseline_scans=sentinel.baseline_scans,
        sigma=sentinel.sigma,
        rel_floor=sentinel.rel_floor,
        abs_floor_seconds=sentinel.abs_floor_seconds,
    )


# ------------------------------------------------------------- trend reports
def trend_report(
    records: "list[dict]",
    *,
    warmup_scans: int = 8,
    baseline_scans: int = 64,
    sigma: float = 3.0,
    rel_floor: float = 0.10,
    abs_floor_seconds: float = 0.05,
) -> dict:
    """Replay a timeline through a FRESH sentinel — the offline twin of the
    serve-side classification (``krr-tpu analyze --trend``,
    ``GET /debug/timeline``, the SIGUSR2 trend artifact, and the bench
    sentinel leg all call this), so online and offline verdicts can't
    drift apart."""
    sentinel = RegressionSentinel(
        warmup_scans=warmup_scans,
        baseline_scans=baseline_scans,
        sigma=sigma,
        rel_floor=rel_floor,
        abs_floor_seconds=abs_floor_seconds,
    )
    verdicts = [sentinel.observe(record, fire=False) for record in records]
    regressions = [v for v in verdicts if v["status"] == "regressed"]
    return {
        "scans": len(records),
        "regressed": len(regressions),
        "regressions": regressions,
        "verdicts": verdicts,
        "status": sentinel.status(),
    }


def render_trend_text(report: dict, records: "Optional[list[dict]]" = None) -> str:
    """Human rendering of a :func:`trend_report` — the ``?format=text`` body
    of ``GET /debug/timeline`` and the default ``analyze --trend`` output."""
    lines = [
        f"scan timeline: {report['scans']} recorded scan(s), "
        f"{report['regressed']} regressed"
    ]
    status = report.get("status") or {}
    for kind, posture in sorted((status.get("baselines") or {}).items()):
        flag = "warm" if posture["warmed"] else f"warming ({posture['observed']} seen)"
        lines.append(f"  baseline[{kind}]: {flag}")
        for name, band in posture["series"].items():
            if name.startswith("phase_"):
                continue
            unit = _VALUE_BANDS.get(name, "s")
            lines.append(
                f"    {name:<16} median {band['median']:>9.3f}{unit} "
                f"± {band['band']:.3f}{unit}  (n={band['samples']})"
            )
    for verdict in report.get("regressions", [])[-16:]:
        lines.append(
            f"  REGRESSED {verdict.get('scan_id') or verdict.get('ts')} "
            f"[{verdict['kind']}]: {verdict['dominant']} +{verdict['sigma']:.1f}σ "
            f"(+{verdict['excess_seconds']:.3f}{verdict.get('excess_unit', 's')}) "
            f"→ {verdict['suspect']}"
        )
    if records:
        tail = records[-8:]
        lines.append(f"  last {len(tail)} scan(s):")
        for record in tail:
            cats = record.get("categories") or {}
            top = max(cats, key=lambda c: cats[c], default=None)
            lines.append(
                f"    ts={record.get('ts')} [{record.get('kind')}] "
                f"wall {record.get('wall', 0.0):.3f}s"
                + (f", top {top} {cats[top]:.3f}s" if top else "")
                + f", rows {record.get('rows', 0)}"
            )
    return "\n".join(lines) + "\n"
