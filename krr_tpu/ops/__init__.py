from krr_tpu.ops import digest, packing, quantile
from krr_tpu.ops.packing import pack_ragged

__all__ = ["digest", "packing", "quantile", "pack_ragged"]
