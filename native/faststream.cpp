// Streaming Prometheus matrix ingest: feed response bytes in ARBITRARY
// chunks as they arrive from the socket; samples fold into per-series
// digest/stats sinks on the fly, so neither the response body nor raw sample
// arrays are ever materialized. This is the streaming form of the buffered
// scanners in fastsamples.cpp (same bucket layout, same label semantics,
// same NaN/Inf dropping) — the buffered one-shot parsers are the oracle its
// tests compare against byte-for-byte.
//
// Design: a resumable state machine with a small carry buffer. The carry
// holds only the bytes the machine cannot yet act on — a partial anchor
// token, an unfinished metric-object label section, or an unfinished
// [ts,"value"] sample — never the body. The metric label section is capped
// (k8s names are <=253 chars; a metric object past 64 KB is rejected as
// malformed rather than buffered unboundedly).
//
// Series state (labels, bucket counts, totals, peaks) lives in arrays OWNED
// by the stream (grown on demand), read out by the Python side after
// finish(). Exposed via a plain C ABI for ctypes.
//
// Build: part of libfastsamples.so (see Makefile).

#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <new>

#include "fastfloat.h"
#include "jsonkey.h"

namespace {

constexpr long kMaxCarry = 64 * 1024;  // metric-object cap; beyond = malformed
constexpr long kMaxNumber = 512;       // longest sample literal we accept (Prometheus
                                       // emits <=25 chars; longer = malformed, BOTH
                                       // number paths below enforce it identically)

enum class State {
  kSeekResult,   // before the "result" array
  kSeekMetric,   // between series: looking for "metric"
  kInMetric,     // inside the metric object: collecting until "values"
  kInValues,     // inside the values section: between samples (depth-tracked)
  kInSample,     // inside [ts,"value"]: skipping the timestamp
  kInNumber,     // collecting the value literal
  kAfterNumber,  // skipping to the sample's closing ']'
  kError,
};

// One series' accumulators. Digest counts live in a single [cap x buckets]
// matrix owned by the stream (indexed by series). [lo, hi] is the touched
// bucket span (hi < lo == no samples folded): real series are band-sparse
// (~tens of active buckets out of thousands), so readout/fold passes that
// honor the span touch ~2% of the dense matrix instead of all of it.
struct SeriesMeta {
  long name_off;  // offset into the names arena ("pod\tcontainer")
  long name_len;
  double total;
  double peak;
  long lo;  // lowest touched bucket (digest mode)
  long hi;  // highest touched bucket, -1 when none
};

struct Stream {
  // Sink configuration: num_buckets == 0 -> stats-only (no histogram).
  double gamma;
  double min_value;
  double inv_log_gamma;
  double inv_min;
  long num_buckets;

  State state = State::kSeekResult;
  //: Bracket depth within the values section: the array opener takes it to
  //: 1, each sample's '[' to 2; back to 0 == this series' values are done.
  //: Disambiguates the array close from a sample close — without it an
  //: empty "values":[] would swallow the next series' metric object.
  long depth = 0;

  // Carry: bytes not yet consumed (partial anchor / metric object / number).
  char* carry = nullptr;
  long carry_len = 0;
  long carry_cap = 0;

  // Series storage, grown on demand.
  SeriesMeta* series = nullptr;
  long series_count = 0;
  long series_cap = 0;
  double* counts = nullptr;  // [series_cap x num_buckets], digest mode only

  // Names arena ("pod\tcontainer" records, not NUL-joined — lengths in meta).
  char* names = nullptr;
  long names_len = 0;
  long names_cap = 0;

  // Current sample literal (kInNumber).
  char number[kMaxNumber + 1];
  long number_len = 0;

  ~Stream() {
    std::free(carry);
    std::free(series);
    std::free(counts);
    std::free(names);
  }

  bool reserve_carry(long need) {
    if (need > kMaxCarry) return false;
    if (need <= carry_cap) return true;
    long cap = carry_cap ? carry_cap : 1024;
    while (cap < need) cap *= 2;
    char* grown = static_cast<char*>(std::realloc(carry, static_cast<size_t>(cap)));
    if (!grown) return false;
    carry = grown;
    carry_cap = cap;
    return true;
  }

  bool grow_series() {
    long cap = series_cap ? series_cap * 2 : 64;
    SeriesMeta* grown =
        static_cast<SeriesMeta*>(std::realloc(series, sizeof(SeriesMeta) * static_cast<size_t>(cap)));
    if (!grown) return false;
    series = grown;
    if (num_buckets > 0) {
      double* grown_counts = static_cast<double*>(
          std::realloc(counts, sizeof(double) * static_cast<size_t>(cap) * static_cast<size_t>(num_buckets)));
      if (!grown_counts) return false;
      counts = grown_counts;
      std::memset(counts + series_cap * num_buckets, 0,
                  sizeof(double) * static_cast<size_t>(cap - series_cap) * static_cast<size_t>(num_buckets));
    }
    series_cap = cap;
    return true;
  }

  // Pre-size for an expected series count BEFORE any series arrive.
  // The counts matrix comes from calloc, not realloc+memset: untouched rows
  // stay lazily-mapped zero pages, so a band-sparse fleet window faults in
  // only the pages its samples actually hit — pre-faulting the full dense
  // [series x buckets] state (2 GB at 100k x 2,560) per window was a
  // measured multi-second cost, paid again at every realloc doubling.
  // A failed reserve must leave the stream EXACTLY as before (series_cap
  // consistent with the allocated sizes): the counts matrix is allocated
  // first, and the meta realloc's failure frees it — so no path commits one
  // allocation without the other.
  bool reserve_series(long n) {
    if (n <= series_cap) return true;
    if (series_count > 0 || n > (1L << 24)) return false;
    double* fresh = nullptr;
    if (num_buckets > 0) {
      fresh = static_cast<double*>(
          std::calloc(static_cast<size_t>(n) * static_cast<size_t>(num_buckets), sizeof(double)));
      if (!fresh) return false;
    }
    SeriesMeta* grown =
        static_cast<SeriesMeta*>(std::realloc(series, sizeof(SeriesMeta) * static_cast<size_t>(n)));
    if (!grown) {
      std::free(fresh);
      return false;
    }
    series = grown;
    if (num_buckets > 0) {
      std::free(counts);
      counts = fresh;
    }
    series_cap = n;
    return true;
  }

  bool append_name(const char* data, long len) {
    if (names_len + len > names_cap) {
      long cap = names_cap ? names_cap : 4096;
      while (cap < names_len + len) cap *= 2;
      char* grown = static_cast<char*>(std::realloc(names, static_cast<size_t>(cap)));
      if (!grown) return false;
      names = grown;
      names_cap = cap;
    }
    std::memcpy(names + names_len, data, static_cast<size_t>(len));
    names_len += len;
    return true;
  }

  void fold_sample(double v) {
    SeriesMeta& m = series[series_count - 1];
    if (num_buckets > 0) {
      long idx = 0;
      if (v > min_value) {
        long raw = static_cast<long>(std::floor(std::log(v * inv_min) * inv_log_gamma));
        if (raw < 0) raw = 0;
        if (raw > num_buckets - 2) raw = num_buckets - 2;
        idx = 1 + raw;
      }
      counts[(series_count - 1) * num_buckets + idx] += 1.0;
      if (idx < m.lo) m.lo = idx;
      if (idx > m.hi) m.hi = idx;
    }
    m.total += 1.0;
    if (v > m.peak) m.peak = v;
  }
};

// Find `needle` in [p, end); returns position or nullptr.
const char* find(const char* p, const char* end, const char* needle, size_t n) {
  if (end - p < static_cast<long>(n)) return nullptr;
  return static_cast<const char*>(memmem(p, static_cast<size_t>(end - p), needle, n));
}

// Label-key scan within a complete metric object [p, limit): identical
// semantics to fastsamples.cpp's find_label_value.
const char* find_label(const char* p, const char* limit, const char* key, size_t key_len,
                       long* len_out) {
  const char* cur = p;
  while (true) {
    const char* hit = find(cur, limit, key, key_len);
    if (!hit) return nullptr;
    const char* start = jsonkey::string_value(hit + key_len, limit, len_out);
    if (start) return start;
    cur = hit + key_len;  // value occurrence — keep scanning
  }
}

// The resumable scanner core: consume as much of [p, end) as possible.
// Returns the first UNCONSUMED position (the caller carries the rest), or
// nullptr on malformed input / allocation failure (state set to kError).
//
// Anchors ("result", "metric", "values") may straddle a chunk boundary: when
// an anchor isn't found, all but the last (anchor_len - 1) bytes are
// consumed, so the partial token survives in the carry.
const char* step(Stream& s, const char* p, const char* end) {
  while (p < end) {
    switch (s.state) {
      case State::kSeekResult: {
        const char* hit = find(p, end, "\"result\"", 8);
        if (!hit) {
          long keep = end - p < 7 ? end - p : 7;
          return end - keep;
        }
        p = hit + 8;
        s.state = State::kSeekMetric;
        break;
      }
      case State::kSeekMetric: {
        const char* hit = find(p, end, "\"metric\"", 8);
        if (!hit) {
          long keep = end - p < 7 ? end - p : 7;
          return end - keep;
        }
        p = hit + 8;
        s.state = State::kInMetric;
        break;
      }
      case State::kInMetric: {
        // Need the WHOLE metric object (through the "values" key) before
        // extracting labels; until then keep everything in the carry. The
        // anchor must be the KEY (next non-space char ':'): a label VALUE
        // equal to "values" — a container legally named "values", reachable
        // since namespace-batched queries put container labels here — would
        // otherwise false-match and mis-extract this series' labels.
        const char* scan = p;
        const char* hit;
        while (true) {
          hit = find(scan, end, "\"values\"", 8);
          if (!hit) return p;  // keep all — bounded by kMaxCarry
          int kind = jsonkey::classify(hit + 8, end, nullptr);
          if (kind < 0) return p;  // can't classify yet — wait for more bytes
          if (kind == 1) break;    // genuine key
          scan = hit + 8;          // value occurrence — keep scanning
        }
        long pod_len = 0, container_len = 0, ns_len = 0;
        const char* pod = find_label(p, hit, "\"pod\"", 5, &pod_len);
        const char* container = find_label(p, hit, "\"container\"", 11, &container_len);
        // Present only on multi-namespace (coalesced) queries grouped by
        // namespace; single-namespace records stay byte-identical
        // ("pod\tcontainer"), so cached row mappings keyed on the names
        // bytes keep working.
        const char* ns = find_label(p, hit, "\"namespace\"", 11, &ns_len);
        if (s.series_count == s.series_cap && !s.grow_series()) {
          s.state = State::kError;
          return nullptr;
        }
        SeriesMeta& m = s.series[s.series_count];
        m.name_off = s.names_len;
        bool ok = (pod_len == 0 || s.append_name(pod, pod_len)) && s.append_name("\t", 1) &&
                  (container_len == 0 || s.append_name(container, container_len)) &&
                  (ns_len == 0 || (s.append_name("\t", 1) && s.append_name(ns, ns_len)));
        if (!ok) {
          s.state = State::kError;
          return nullptr;
        }
        m.name_len = s.names_len - m.name_off;
        m.total = 0.0;
        m.peak = -HUGE_VAL;
        m.lo = s.num_buckets;
        m.hi = -1;
        s.series_count++;
        p = hit + 8;
        s.depth = 0;
        s.state = State::kInValues;
        break;
      }
      case State::kInValues: {
        // Tight scan to the next bracket (the switch dispatch per byte
        // halves throughput vs the buffered scanner; these inner loops
        // close most of the gap).
        //
        // FAST LANE: while each sample's closing ']' is provably inside this
        // chunk, whole [ts,"value"] pairs parse inline in one loop — one
        // memchr + one float parse per sample, with the series' accumulators
        // hoisted out of the per-sample path — instead of four state
        // transitions and a re-derived row pointer each (measured ~2x feed
        // throughput at fleet scale). Semantics are identical to the
        // kInSample/kInNumber/kAfterNumber states (same fast-float + strtod
        // fallback, same finite-only fold, same degenerate-[ts] handling);
        // samples straddling the chunk edge take the stepwise states as
        // before, which the every-chunk-size equivalence tests pin.
        if (s.depth == 1 && s.series_count > 0) {
          SeriesMeta& m = s.series[s.series_count - 1];
          double* row = s.num_buckets > 0 ? s.counts + (s.series_count - 1) * s.num_buckets : nullptr;
          const double inv_log_gamma = s.inv_log_gamma;
          const double inv_min = s.inv_min;
          const double min_value = s.min_value;
          const long top = s.num_buckets - 2;
          long lo = m.lo, hi = m.hi;  // span hoisted like the row pointer
          while (true) {
            while (p < end && *p != '[' && *p != ']') p++;
            if (p >= end || *p == ']') break;  // array close / chunk edge: stepwise
            const char* close = static_cast<const char*>(
                memchr(p + 1, ']', static_cast<size_t>(end - (p + 1))));
            if (!close) break;  // sample straddles the chunk: stepwise states
            const char* q = p + 1;
            while (q < close && *q != ',') q++;  // timestamp bytes
            if (q < close) {
              q++;
              while (q < close && (*q == ' ' || *q == '"')) q++;
              // The kMaxNumber literal cap is enforced on BOTH lanes so an
              // over-cap literal fails the stream whether or not it
              // straddles a chunk: here post-checked against the consumed
              // length on the fast parse (no extra scan on the hot path)
              // and pre-checked on the rare fallback.
              double v;
              const char* after = fastfloat::parse_number_fast(q, close, &v);
              if (after) {
                // Cap the FULL terminator-bounded literal run, exactly like
                // the stepwise kInNumber extent — capping only the parsed
                // prefix would let an over-cap garbage-suffixed literal
                // pass here but hard-error when chunked through the
                // stepwise states. For well-formed literals `after` already
                // sits on the terminator, so this loop is zero iterations.
                const char* lit_end = after;
                while (lit_end < close && *lit_end != '"' && *lit_end != ',') lit_end++;
                if (lit_end - q > kMaxNumber) {
                  s.state = State::kError;
                  return nullptr;
                }
              } else if (close > q) {
                const char* lit_end = q;
                while (lit_end < close && *lit_end != '"' && *lit_end != ',') lit_end++;
                if (lit_end - q > kMaxNumber) {
                  s.state = State::kError;
                  return nullptr;
                }
                long n = lit_end - q;
                std::memcpy(s.number, q, static_cast<size_t>(n));
                s.number[n] = '\0';
                char* slow_end = nullptr;
                v = std::strtod(s.number, &slow_end);
                after = slow_end == s.number ? nullptr : slow_end;
              }
              if (after && std::isfinite(v)) {
                // Inline fold_sample with the hoisted row/meta.
                if (row) {
                  long idx = 0;
                  if (v > min_value) {
                    long raw = static_cast<long>(std::floor(std::log(v * inv_min) * inv_log_gamma));
                    if (raw < 0) raw = 0;
                    if (raw > top) raw = top;
                    idx = 1 + raw;
                  }
                  row[idx] += 1.0;
                  if (idx < lo) lo = idx;
                  if (idx > hi) hi = idx;
                }
                m.total += 1.0;
                if (v > m.peak) m.peak = v;
              }
            }
            // Degenerate [ts] pair (no comma): sample-less, like kInSample.
            p = close + 1;
          }
          m.lo = lo;
          m.hi = hi;
        }
        while (p < end && *p != '[' && *p != ']') p++;
        if (p >= end) break;
        if (*p == '[') {
          p++;
          s.depth++;
          if (s.depth >= 2) s.state = State::kInSample;  // a sample's opener
        } else {
          p++;
          s.depth--;
          if (s.depth <= 0) s.state = State::kSeekMetric;  // values array closed
        }
        break;
      }
      case State::kInSample: {
        while (p < end && *p != ',' && *p != ']') p++;  // timestamp bytes
        if (p >= end) break;
        if (*p == ',') {
          p++;
          s.number_len = 0;
          s.state = State::kInNumber;
        } else {
          p++;  // degenerate [ts] pair — treat as sample-less
          s.depth--;
          s.state = State::kInValues;
        }
        break;
      }
      case State::kInNumber: {
        if (s.number_len == 0) {
          while (p < end && (*p == ' ' || *p == '"')) p++;
          if (p >= end) break;
          const char* t = p;
          while (t < end && *t != ']' && *t != ',' && *t != '"') t++;
          if (t < end) {
            // Whole literal in view (the overwhelmingly common case):
            // parse IN PLACE — no per-character copy.
            if (t - p > kMaxNumber) {  // same limit as the copy path below
              s.state = State::kError;
              return nullptr;
            }
            double v;
            const char* after = fastfloat::parse_number_fast(p, t, &v);
            if (!after && t > p) {
              // strtod fallback needs NUL termination: bounce via the buffer.
              long n = t - p;
              std::memcpy(s.number, p, static_cast<size_t>(n));
              s.number[n] = '\0';
              char* slow_end = nullptr;
              v = std::strtod(s.number, &slow_end);
              after = slow_end == s.number ? nullptr : slow_end;
            }
            if (after && std::isfinite(v)) s.fold_sample(v);
            p = t;
            s.state = State::kAfterNumber;
            break;
          }
          // Literal straddles the chunk: fall through to the copy path.
        }
        while (p < end) {
          char c = *p;
          if (c == ' ' || c == '"') {
            p++;
          } else if (c == ']' || c == ',') {
            break;
          } else {
            if (s.number_len >= kMaxNumber) {
              s.state = State::kError;
              return nullptr;
            }
            s.number[s.number_len++] = c;
            p++;
          }
        }
        if (p >= end) break;  // literal continues in the next chunk
        // Literal complete: parse and fold (same fast-float + strtod
        // fallback and finite-only rule as the buffered scanner).
        s.number[s.number_len] = '\0';
        double v;
        const char* after =
            fastfloat::parse_number_fast(s.number, s.number + s.number_len, &v);
        if (!after) {
          char* slow_end = nullptr;
          v = std::strtod(s.number, &slow_end);
          after = slow_end == s.number ? nullptr : slow_end;
        }
        if (after && std::isfinite(v)) s.fold_sample(v);
        s.number_len = 0;
        s.state = State::kAfterNumber;
        break;
      }
      case State::kAfterNumber: {
        while (p < end && *p != ']') p++;
        if (p >= end) break;
        p++;
        s.depth--;
        s.state = State::kInValues;
        break;
      }
      case State::kError:
        return nullptr;
    }
  }
  return end;
}

}  // namespace

extern "C" {

void* krr_stream_new(double gamma, double min_value, long num_buckets) {
  // num_buckets == 0 selects the stats-only sink (count + max, no histogram);
  // otherwise parameters follow krr_parse_matrix_digest.
  if (num_buckets != 0 && (num_buckets < 2 || gamma <= 1.0 || min_value <= 0.0)) return nullptr;
  Stream* s = new (std::nothrow) Stream();
  if (!s) return nullptr;
  s->gamma = gamma;
  s->min_value = min_value;
  s->num_buckets = num_buckets;
  if (num_buckets > 0) {
    s->inv_log_gamma = 1.0 / std::log(gamma);
    s->inv_min = 1.0 / min_value;
  }
  return s;
}

// Feed one chunk. Returns 0, or -2 on malformed input/allocation failure
// (the stream is then unusable).
//
// The carry never exceeds kMaxCarry regardless of chunk size: while a carry
// exists, new bytes top it up in kMaxCarry-bounded blocks and the machine
// steps over the carry buffer; once it drains, the rest of the chunk is
// scanned in place. The machine makes progress in any full carry unless a
// single metric object exceeds kMaxCarry — which is rejected as malformed,
// never buffered unboundedly.
long krr_stream_feed(void* handle, const char* chunk, long len) {
  Stream& s = *static_cast<Stream*>(handle);
  if (s.state == State::kError) return -2;

  const char* p = chunk;
  const char* end = chunk + len;
  while (p < end) {
    if (s.carry_len > 0) {
      long room = kMaxCarry - s.carry_len;
      long take = end - p < room ? end - p : room;
      if (take <= 0) {  // carry at cap with no progress possible
        s.state = State::kError;
        return -2;
      }
      if (!s.reserve_carry(s.carry_len + take)) {
        s.state = State::kError;
        return -2;
      }
      std::memcpy(s.carry + s.carry_len, p, static_cast<size_t>(take));
      s.carry_len += take;
      p += take;
      const char* consumed_to = step(s, s.carry, s.carry + s.carry_len);
      if (!consumed_to) return -2;
      long remaining = (s.carry + s.carry_len) - consumed_to;
      if (remaining == s.carry_len && remaining >= kMaxCarry) {
        s.state = State::kError;  // a metric object larger than the cap
        return -2;
      }
      std::memmove(s.carry, consumed_to, static_cast<size_t>(remaining));
      s.carry_len = remaining;
      continue;
    }
    const char* consumed_to = step(s, p, end);
    if (!consumed_to) return -2;
    long remaining = end - consumed_to;
    if (remaining > 0) {
      if (remaining > kMaxCarry || !s.reserve_carry(remaining)) {
        s.state = State::kError;  // a metric object larger than the cap
        return -2;
      }
      std::memcpy(s.carry, consumed_to, static_cast<size_t>(remaining));
      s.carry_len = remaining;
    }
    return 0;  // chunk fully handed off (scanned or carried)
  }
  return 0;
}

// End of body: returns the series count, -2 if the stream errored or never
// saw a "result" array (e.g. an error payload), or -3 if the body ended
// MID-SERIES — a truncated response (a proxy or server cut the body with
// consistent framing). Accepting the partial fold would silently lose the
// tail's samples behind a "successful" parse; callers must fail the query
// and refetch instead.
long krr_stream_finish(void* handle) {
  Stream& s = *static_cast<Stream*>(handle);
  if (s.state == State::kError || s.state == State::kSeekResult) return -2;
  if (s.state != State::kSeekMetric) return -3;
  // A trailing carry is fine in kSeekMetric: it can only hold a partial
  // anchor between series (never part of an accepted sample).
  return s.series_count;
}

//   names      — '\n'-joined "pod\tcontainer" records (as fastsamples.cpp)
//   totals/peaks — per-series count / exact max
//   counts     — [series x num_buckets] row-major (digest mode only)
// Buffers are caller-allocated; returns 0 or -1 if a capacity is too small.
long krr_stream_read(void* handle, char* names, long names_cap, double* totals, double* peaks,
                     double* counts, long series_cap) {
  Stream& s = *static_cast<Stream*>(handle);
  if (s.series_count > series_cap) return -1;
  long need = s.names_len + s.series_count;  // + '\n' per record
  if (need > names_cap) return -1;
  long off = 0;
  for (long i = 0; i < s.series_count; i++) {
    std::memcpy(names + off, s.names + s.series[i].name_off,
                static_cast<size_t>(s.series[i].name_len));
    off += s.series[i].name_len;
    names[off++] = '\n';
    totals[i] = s.series[i].total;
    peaks[i] = s.series[i].peak;
  }
  if (s.num_buckets > 0 && counts) {
    std::memcpy(counts, s.counts,
                sizeof(double) * static_cast<size_t>(s.series_count) * static_cast<size_t>(s.num_buckets));
  }
  return 0;
}

long krr_stream_names_len(void* handle) {
  Stream& s = *static_cast<Stream*>(handle);
  return s.names_len + s.series_count;
}

// Pre-size the stream for an expected series count (call right after
// krr_stream_new, before any bytes). Returns 0; -1 when the hint can't be
// honored (already holding series, absurd count, OOM) — growth-on-demand
// still works then. The win is twofold: no realloc-doubling copies, and a
// calloc'd counts matrix whose untouched pages are never faulted (see
// Stream::reserve_series).
long krr_stream_reserve(void* handle, long n_series) {
  Stream& s = *static_cast<Stream*>(handle);
  if (s.state == State::kError) return -1;
  if (n_series <= 0) return 0;
  return s.reserve_series(n_series) ? 0 : -1;
}

// Fold the per-series bucket counts straight into caller-owned accumulator
// rows: series i adds its touched bucket span into row rows[i] of
// dst_counts ([n_rows x num_buckets] float64, row-major); rows[i] < 0 skips
// the series. This replaces the dense readout-copy + Python-side add with
// ONE band-sparse pass — the only full-matrix traversal left in the
// streamed ingest. Digest mode only; rows must cover every series. Returns
// 0, or -1 on a shape/mode mismatch.
long krr_stream_fold_into(void* handle, const long* rows, long n_series, double* dst_counts,
                          long n_rows) {
  Stream& s = *static_cast<Stream*>(handle);
  if (s.num_buckets <= 0 || n_series != s.series_count) return -1;
  for (long i = 0; i < n_series; i++) {
    long r = rows[i];
    if (r < 0) continue;
    if (r >= n_rows) return -1;
    const SeriesMeta& m = s.series[i];
    if (m.hi < m.lo) continue;  // no samples folded into this series
    const double* src = s.counts + i * s.num_buckets;
    double* dst = dst_counts + r * s.num_buckets;
    for (long b = m.lo; b <= m.hi; b++) dst[b] += src[b];
  }
  return 0;
}

void krr_stream_free(void* handle) { delete static_cast<Stream*>(handle); }

}  // extern "C"

// ---------------------------------------------------------------------------
// Prometheus remote-write scanner: snappy block format + the WriteRequest
// protobuf, hand-rolled beside the JSON scanner above (same ownership rules:
// caller-allocated output buffers, negative return codes, Python fallback on
// capacity shortfall). The wire is snappy-compressed protobuf --
// WriteRequest{ repeated TimeSeries{ repeated Label{name,value},
// repeated Sample{double value, int64 timestamp_ms} } } -- and the decode is
// a single pass: decompress into one scratch buffer sized from the snappy
// preamble, then walk the protobuf emitting flat sample/label arrays. No
// digesting here: the ingest plane evaluates samples onto the serve grid
// later, so the decoder's job is only a faithful, bounded, crash-proof
// unpack (malformed bytes are a -2, never UB -- every read is bounds-checked
// against the decoded buffer).

namespace {

// Parse the uvarint at [p, end); advances *p. False on truncation/overflow
// (>10 bytes or a value that doesn't fit uint64).
bool read_varint(const unsigned char** p, const unsigned char* end, unsigned long long* out) {
  unsigned long long v = 0;
  int shift = 0;
  while (*p < end && shift < 64) {
    unsigned char b = *(*p)++;
    v |= static_cast<unsigned long long>(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return true;
    }
    shift += 7;
  }
  return false;
}

// Snappy BLOCK format (the remote-write framing): uvarint uncompressed
// length, then literal / copy tags. Decompresses [src, src_end) into dst
// (caller-sized to the preamble's length). Returns false on any malformed
// element: truncated tag payloads, copies reaching before the output start,
// or output over/underrun.
bool snappy_decompress(const unsigned char* src, const unsigned char* src_end,
                       unsigned char* dst, long long dst_len) {
  unsigned long long expect = 0;
  if (!read_varint(&src, src_end, &expect) ||
      expect != static_cast<unsigned long long>(dst_len)) {
    return false;
  }
  long long out = 0;
  while (src < src_end) {
    unsigned char tag = *src++;
    long long len;
    if ((tag & 3) == 0) {  // literal
      len = (tag >> 2) + 1;
      if (len > 60) {
        int extra = static_cast<int>(len - 60);  // 1..4 length bytes, LE
        if (src_end - src < extra) return false;
        len = 0;
        for (int i = 0; i < extra; i++) len |= static_cast<long long>(src[i]) << (8 * i);
        len += 1;
        src += extra;
      }
      if (src_end - src < len || dst_len - out < len) return false;
      std::memcpy(dst + out, src, static_cast<size_t>(len));
      src += len;
      out += len;
    } else {  // copy: 1/2/4-byte offsets
      long long offset;
      if ((tag & 3) == 1) {
        len = ((tag >> 2) & 7) + 4;
        if (src >= src_end) return false;
        offset = (static_cast<long long>(tag >> 5) << 8) | *src++;
      } else if ((tag & 3) == 2) {
        len = (tag >> 2) + 1;
        if (src_end - src < 2) return false;
        offset = src[0] | (static_cast<long long>(src[1]) << 8);
        src += 2;
      } else {
        len = (tag >> 2) + 1;
        if (src_end - src < 4) return false;
        offset = src[0] | (static_cast<long long>(src[1]) << 8) |
                 (static_cast<long long>(src[2]) << 16) | (static_cast<long long>(src[3]) << 24);
        src += 4;
      }
      if (offset <= 0 || offset > out || dst_len - out < len) return false;
      // Overlapping copies are the RLE idiom (offset < len): byte-at-a-time
      // forward copy is the defined semantics.
      const unsigned char* from = dst + out - offset;
      for (long long i = 0; i < len; i++) dst[out + i] = from[i];
      out += len;
    }
  }
  return out == dst_len;
}

// Skip one protobuf field of wire type `wt` at [p, end). Groups (wt 3/4) and
// unknown types are malformed -- nothing in the remote-write schema emits
// them, and skipping blind would desync the stream.
bool skip_field(const unsigned char** p, const unsigned char* end, unsigned int wt) {
  unsigned long long n = 0;
  switch (wt) {
    case 0:  // varint
      return read_varint(p, end, &n);
    case 1:  // fixed64
      if (end - *p < 8) return false;
      *p += 8;
      return true;
    case 2:  // length-delimited
      if (!read_varint(p, end, &n) || static_cast<unsigned long long>(end - *p) < n) return false;
      *p += n;
      return true;
    case 5:  // fixed32
      if (end - *p < 4) return false;
      *p += 4;
      return true;
    default:
      return false;
  }
}

struct RwOut {
  char* names;
  long long names_cap;
  long long names_len = 0;
  double* values;
  long long* timestamps;
  long long values_cap;
  long long values_n = 0;
  long long* lens;
  long long series_cap;
  long long series_n = 0;
};

// One Label submessage: append "name\tvalue" (with the leading separator the
// caller chose) to the names arena. Separator bytes inside a label would
// corrupt the record framing, so they are malformed here AND in the Python
// twin -- the parity contract covers rejects too.
int parse_label(const unsigned char* p, const unsigned char* end, RwOut& o, bool first) {
  const unsigned char* name = nullptr;
  const unsigned char* value = nullptr;
  unsigned long long name_len = 0, value_len = 0;
  while (p < end) {
    unsigned long long key = 0;
    if (!read_varint(&p, end, &key)) return -2;
    unsigned int field = static_cast<unsigned int>(key >> 3), wt = key & 7;
    if ((field == 1 || field == 2) && wt == 2) {
      unsigned long long n = 0;
      if (!read_varint(&p, end, &n) || static_cast<unsigned long long>(end - p) < n) return -2;
      if (field == 1) {
        name = p;
        name_len = n;
      } else {
        value = p;
        value_len = n;
      }
      p += n;
    } else if (!skip_field(&p, end, wt)) {
      return -2;
    }
  }
  for (unsigned long long i = 0; i < name_len; i++) {
    if (name[i] == '\t' || name[i] == '\n') return -2;
  }
  for (unsigned long long i = 0; i < value_len; i++) {
    if (value[i] == '\t' || value[i] == '\n') return -2;
  }
  long long need = o.names_len + static_cast<long long>(name_len + value_len) + 2 + (first ? 0 : 1);
  if (need > o.names_cap) return -1;
  if (!first) o.names[o.names_len++] = '\t';
  if (name_len) std::memcpy(o.names + o.names_len, name, name_len);
  o.names_len += name_len;
  o.names[o.names_len++] = '\t';
  if (value_len) std::memcpy(o.names + o.names_len, value, value_len);
  o.names_len += value_len;
  return 0;
}

// One Sample submessage. Missing fields take protobuf defaults (value 0.0,
// timestamp 0), matching the Python twin.
int parse_sample(const unsigned char* p, const unsigned char* end, RwOut& o) {
  double v = 0.0;
  long long ts = 0;
  while (p < end) {
    unsigned long long key = 0;
    if (!read_varint(&p, end, &key)) return -2;
    unsigned int field = static_cast<unsigned int>(key >> 3), wt = key & 7;
    if (field == 1 && wt == 1) {
      if (end - p < 8) return -2;
      std::memcpy(&v, p, 8);  // protobuf doubles are little-endian IEEE 754
      p += 8;
    } else if (field == 2 && wt == 0) {
      unsigned long long raw = 0;
      if (!read_varint(&p, end, &raw)) return -2;
      ts = static_cast<long long>(raw);  // int64: two's-complement passthrough
    } else if (!skip_field(&p, end, wt)) {
      return -2;
    }
  }
  if (o.values_n >= o.values_cap) return -1;
  o.values[o.values_n] = v;
  o.timestamps[o.values_n] = ts;
  o.values_n++;
  return 0;
}

int parse_timeseries(const unsigned char* p, const unsigned char* end, RwOut& o) {
  if (o.series_n >= o.series_cap) return -1;
  long long samples_before = o.values_n;
  bool first_label = true;
  while (p < end) {
    unsigned long long key = 0;
    if (!read_varint(&p, end, &key)) return -2;
    unsigned int field = static_cast<unsigned int>(key >> 3), wt = key & 7;
    if ((field == 1 || field == 2) && wt == 2) {
      unsigned long long n = 0;
      if (!read_varint(&p, end, &n) || static_cast<unsigned long long>(end - p) < n) return -2;
      int rc = field == 1 ? parse_label(p, p + n, o, first_label)
                          : parse_sample(p, p + n, o);
      if (rc != 0) return rc;
      if (field == 1) first_label = false;
      p += n;
    } else if (!skip_field(&p, end, wt)) {
      return -2;
    }
  }
  o.lens[o.series_n] = o.values_n - samples_before;
  o.series_n++;
  return 0;
}

}  // namespace

extern "C" {

// The snappy preamble's uncompressed length, or -2 when the body is too
// short / the varint is malformed. Callers size the decode buffers from it.
long long krr_rw_uncompressed_len(const unsigned char* body, long long body_len) {
  const unsigned char* p = body;
  unsigned long long n = 0;
  if (!read_varint(&p, body + body_len, &n) || n > (1ULL << 62)) return -2;
  return static_cast<long long>(n);
}

// Decode one remote-write body (snappy-compressed WriteRequest) into flat
// arrays:
//   names        — '\n'-joined per-series records of '\t'-joined label
//                  name/value fields, in wire order (the record framing the
//                  JSON scanner's readout uses)
//   values/timestamps — every sample, series-major (timestamps in ms)
//   lens         — per-series sample counts
// Returns the series count (>= 0), -1 when a caller buffer is too small
// (retry via the Python fallback), -2 on malformed snappy/protobuf bytes, or
// -3 when the uncompressed length exceeds max_decoded (a decompression bomb
// — reject, don't allocate).
long long krr_rw_decode(const unsigned char* body, long long body_len, long long max_decoded,
                        char* names, long long names_cap, double* values,
                        long long* timestamps, long long values_cap, long long* lens,
                        long long series_cap, long long* out_values_n,
                        long long* out_names_len) {
  long long decoded_len = krr_rw_uncompressed_len(body, body_len);
  if (decoded_len < 0) return -2;
  if (decoded_len > max_decoded) return -3;
  unsigned char* decoded =
      static_cast<unsigned char*>(std::malloc(decoded_len ? static_cast<size_t>(decoded_len) : 1));
  if (!decoded) return -2;
  if (!snappy_decompress(body, body + body_len, decoded, decoded_len)) {
    std::free(decoded);
    return -2;
  }

  RwOut o;
  o.names = names;
  o.names_cap = names_cap;
  o.values = values;
  o.timestamps = timestamps;
  o.values_cap = values_cap;
  o.lens = lens;
  o.series_cap = series_cap;

  const unsigned char* p = decoded;
  const unsigned char* end = decoded + decoded_len;
  int rc = 0;
  while (p < end) {
    unsigned long long key = 0;
    if (!read_varint(&p, end, &key)) {
      rc = -2;
      break;
    }
    unsigned int field = static_cast<unsigned int>(key >> 3), wt = key & 7;
    if (field == 1 && wt == 2) {  // repeated TimeSeries
      unsigned long long n = 0;
      if (!read_varint(&p, end, &n) || static_cast<unsigned long long>(end - p) < n) {
        rc = -2;
        break;
      }
      if (o.names_len >= o.names_cap) {
        rc = -1;
        break;
      }
      if (o.series_n > 0) o.names[o.names_len++] = '\n';
      rc = parse_timeseries(p, p + n, o);
      if (rc != 0) break;
      p += n;
    } else if (!skip_field(&p, end, wt)) {  // metadata etc.: skipped
      rc = -2;
      break;
    }
  }
  std::free(decoded);
  if (rc != 0) return rc;
  *out_values_n = o.values_n;
  *out_names_len = o.names_len;
  return o.series_n;
}

// Digest a plain double array with the EXACT arithmetic of
// krr_parse_matrix_digest's sample sink (same expression order, same libm
// calls) — the push ingest plane's fold path, so push-fed windows bucket
// bit-identically to range-fetched ones regardless of borderline log()
// roundings. counts ([num_buckets]) must be zero-initialized by the caller.
// Returns 0, or -2 on invalid digest parameters.
long long krr_digest_array(const double* values, long long n, double gamma,
                           double min_value, long long num_buckets,
                           double* counts, double* out_total, double* out_peak) {
  if (num_buckets < 2 || gamma <= 1.0 || min_value <= 0.0) return -2;
  const double inv_log_gamma = 1.0 / std::log(gamma);
  const double inv_min = 1.0 / min_value;
  double peak = -HUGE_VAL;
  for (long long i = 0; i < n; ++i) {
    const double v = values[i];
    long long idx = 0;
    if (v > min_value) {
      long long raw =
          static_cast<long long>(std::floor(std::log(v * inv_min) * inv_log_gamma));
      if (raw < 0) raw = 0;
      if (raw > num_buckets - 2) raw = num_buckets - 2;
      idx = 1 + raw;
    }
    counts[idx] += 1.0;
    if (v > peak) peak = v;
  }
  *out_total = static_cast<double>(n);
  *out_peak = peak;
  return 0;
}

}  // extern "C"
