"""Streaming / multi-source / checkpoint-resume tests (BASELINE.md config 5)."""

import numpy as np
import pytest

from krr_tpu.core.streaming import DigestStore, object_key
from krr_tpu.models import FleetBatch, K8sObjectData, ResourceAllocations, ResourceType
from krr_tpu.ops import digest as digest_ops
from krr_tpu.ops.digest import DigestSpec
from krr_tpu.strategies import TDigestStrategy, TDigestStrategySettings

SPEC = DigestSpec(gamma=1.01, min_value=1e-7, num_buckets=2560)


def make_obj(name: str, pods: list[str]) -> K8sObjectData:
    return K8sObjectData(
        cluster="c", namespace="ns", name=name, kind="Deployment", container="main", pods=pods,
        allocations=ResourceAllocations(requests={}, limits={}),
    )


def window_batch(rng, objects: list[K8sObjectData], t: int) -> FleetBatch:
    cpu = [{pod: rng.gamma(2.0, 0.05, size=t) for pod in obj.pods} for obj in objects]
    mem = [{pod: rng.uniform(5e7, 3e8, size=t) for pod in obj.pods} for obj in objects]
    return FleetBatch.build(objects, {ResourceType.CPU: cpu, ResourceType.Memory: mem})


class TestDigestStore:
    def test_save_load_roundtrip(self, tmp_path, rng):
        store = DigestStore(spec=SPEC, keys=["a", "b"])
        store.cpu_counts[:] = rng.integers(0, 5, size=store.cpu_counts.shape)
        store.cpu_total[:] = store.cpu_counts.sum(axis=1)
        store.cpu_peak[:] = [0.5, 1.5]
        store.mem_total[:] = [10, 0]
        store.mem_peak[:] = [100.0, -np.inf]
        path = str(tmp_path / "state.npz")
        store.save(path)
        loaded = DigestStore.load(path)
        assert loaded.keys == ["a", "b"]
        np.testing.assert_array_equal(loaded.cpu_counts, store.cpu_counts)
        np.testing.assert_array_equal(loaded.mem_peak, store.mem_peak)

    def test_legacy_dense_state_still_loads(self, tmp_path, rng):
        """Round-3 state files stored the count matrix dense under zlib; the
        sparse CSR format must keep loading them."""
        import json

        keys = ["a", "b", "c"]
        counts = rng.integers(0, 5, size=(3, SPEC.num_buckets)).astype(np.float32)
        path = str(tmp_path / "legacy.npz")
        with open(path, "wb") as f:
            np.savez_compressed(
                f,
                meta=json.dumps({"gamma": SPEC.gamma, "min_value": SPEC.min_value,
                                 "num_buckets": SPEC.num_buckets}),
                keys=np.asarray(keys),
                cpu_counts=counts,
                cpu_total=counts.sum(axis=1),
                cpu_peak=np.array([0.5, 1.5, -np.inf], np.float32),
                mem_total=np.array([10, 0, 3], np.float32),
                mem_peak=np.array([100.0, -np.inf, 7.0], np.float32),
            )
        loaded = DigestStore.load(path)
        assert loaded.keys == keys
        np.testing.assert_array_equal(loaded.cpu_counts, counts)
        np.testing.assert_array_equal(loaded.mem_peak, [100.0, -np.inf, 7.0])
        # And a save in the new format round-trips the same state.
        new_path = str(tmp_path / "new.npz")
        loaded.save(new_path)
        reloaded = DigestStore.load(new_path)
        np.testing.assert_array_equal(reloaded.cpu_counts, counts)
        np.testing.assert_array_equal(reloaded.cpu_total, loaded.cpu_total)

    def test_sparse_format_is_uncompressed_csr(self, tmp_path, rng):
        """The state file stores occupied buckets only (CSR), uncompressed —
        the round-4 fix for the ~10 s zlib save+load cycle at 100k rows."""
        store = DigestStore(spec=SPEC, keys=["a", "b"])
        store.cpu_counts[0, 7] = 3.0
        store.cpu_counts[1, 2559] = 1.0
        path = str(tmp_path / "state.npz")
        store.save(path)
        with np.load(path, allow_pickle=False) as data:
            assert "cpu_counts" not in data.files
            np.testing.assert_array_equal(data["csr_vals"], [3.0, 1.0])
            np.testing.assert_array_equal(data["csr_cols"], [7, 2559])
            np.testing.assert_array_equal(data["csr_indptr"], [0, 1, 2])
        # Uncompressed for real: zlib over the (mostly-small) arrays cost
        # ~10 s per save+load cycle at 100k rows — a savez_compressed
        # regression must fail here, not just re-shrink the file.
        import zipfile

        with zipfile.ZipFile(path) as zf:
            assert all(info.compress_type == zipfile.ZIP_STORED for info in zf.infolist())

    def test_noncontiguous_query_matches_contiguous(self, rng):
        """_take's contiguous fast path and the fancy-index fallback must
        agree (including a single-row and an out-of-order subset)."""
        n = 50
        store = DigestStore(spec=SPEC, keys=[f"k{i}" for i in range(n)])
        store.cpu_counts[:] = rng.integers(0, 9, size=store.cpu_counts.shape)
        store.cpu_total[:] = store.cpu_counts.sum(axis=1)
        store.cpu_peak[:] = rng.gamma(2.0, 0.3, n)
        full = store.cpu_percentile(np.arange(n), 99.0)
        scattered = np.array([41, 3, 17, 3, 0, n - 1])
        np.testing.assert_array_equal(store.cpu_percentile(scattered, 99.0), full[scattered])
        np.testing.assert_array_equal(store.cpu_percentile(np.array([7]), 99.0), full[[7]])
        np.testing.assert_array_equal(
            store.cpu_percentile(np.arange(10, 20), 99.0), full[10:20]
        )

    def test_out_of_range_rows_still_raise(self):
        """The contiguous fast path must not let slice semantics silently
        truncate out-of-range rows where fancy indexing raises."""
        store = DigestStore(spec=SPEC, keys=["a", "b"])
        with pytest.raises(IndexError):
            store.cpu_percentile(np.array([1, 2]), 99.0)

    def test_shuffled_remerge_equals_ordered(self, rng):
        """A re-scan that returns the fleet in a different order must land on
        the same rows (non-contiguous scatter path) — and a window carrying a
        duplicate unseen key must grow ONE row, not one per occurrence
        (regression: the dup used to orphan a row and misroute the merge)."""
        ones = np.ones((2, SPEC.num_buckets), np.float32)
        store = DigestStore(spec=SPEC)
        store.merge_window(["x", "y"], ones, np.array([8.0, 8.0]), np.array([1.0, 2.0]),
                           np.array([8.0, 8.0]), np.array([5.0, 3.0]))
        store.merge_window(["y", "x"], ones, np.array([8.0, 8.0]), np.array([9.0, 1.0]),
                           np.array([8.0, 8.0]), np.array([1.0, 9.0]))
        assert store.keys == ["x", "y"]
        np.testing.assert_array_equal(store.cpu_total, [16.0, 16.0])
        np.testing.assert_array_equal(store.cpu_peak, [1.0, 9.0])
        np.testing.assert_array_equal(store.mem_peak, [9.0, 3.0])

        dup = DigestStore(spec=SPEC)
        rows = dup.merge_window(["a", "a"], ones, np.array([8.0, 8.0]), np.array([1.0, 2.0]),
                                np.array([8.0, 8.0]), np.array([5.0, 3.0]))
        assert list(rows) == [0, 0] and dup.keys == ["a"]
        assert dup.cpu_counts[0].sum() == 2 * SPEC.num_buckets
        assert dup.cpu_peak[0] == 2.0 and dup.mem_peak[0] == 5.0

    def test_incremental_windows_equal_oneshot(self, rng):
        """4 disjoint windows (4 'Prometheus sources') merged in any order
        must equal one digest over the concatenated history — exactly."""
        t = 512
        windows = [rng.gamma(2.0, 0.05, size=(3, t)).astype(np.float32) for _ in range(4)]
        counts = np.full(3, t, dtype=np.int32)

        store = DigestStore(spec=SPEC)
        keys = ["x", "y", "z"]
        order = [2, 0, 3, 1]  # merge out of order: merges must commute
        for w in order:
            d = digest_ops.build_from_packed(SPEC, windows[w], counts, chunk_size=128)
            rows = store.merge_window(
                keys,
                np.asarray(d.counts),
                np.asarray(d.total),
                np.asarray(d.peak),
                counts.astype(np.float32),
                np.zeros(3, np.float32),
            )

        full = np.concatenate(windows, axis=1)
        d_full = digest_ops.build_from_packed(SPEC, full, np.full(3, 4 * t, np.int32), chunk_size=128)
        np.testing.assert_array_equal(store.cpu_counts[rows], np.asarray(d_full.counts))
        np.testing.assert_array_equal(store.cpu_total[rows], np.asarray(d_full.total))
        np.testing.assert_array_equal(store.cpu_peak[rows], np.asarray(d_full.peak))

        # Quantile from the merged store matches the one-shot device estimate.
        np.testing.assert_allclose(
            store.cpu_percentile(rows, 99.0),
            np.asarray(digest_ops.percentile(SPEC, d_full, 99.0)),
            rtol=1e-6,
        )

    def test_spec_mismatch_raises(self, tmp_path):
        path = str(tmp_path / "state.npz")
        DigestStore(spec=SPEC).save(path)
        other = DigestSpec(gamma=1.02, min_value=1e-7, num_buckets=2560)
        with pytest.raises(ValueError, match="incompatible"):
            DigestStore.open_or_create(path, other)


class TestStatefulStrategy:
    def test_two_windows_accumulate_and_fleet_grows(self, tmp_path, rng):
        path = str(tmp_path / "state.npz")
        settings = TDigestStrategySettings(state_path=path, chunk_size=128)
        strategy = TDigestStrategy(settings)

        obj_a = make_obj("a", ["a-0"])
        obj_b = make_obj("b", ["b-0"])

        # Window 1: only object a, low cpu values.
        batch1 = window_batch(rng, [obj_a], t=256)
        r1 = strategy.run_batch(batch1)[0]

        # Window 2: a and a brand-new b; a gets much hotter cpu.
        cpu_hot = {"a-0": rng.gamma(2.0, 0.5, size=256)}
        mem2 = {"a-0": rng.uniform(5e7, 3e8, size=256)}
        batch2 = FleetBatch.build(
            [obj_a, obj_b],
            {
                ResourceType.CPU: [cpu_hot, {"b-0": rng.gamma(2.0, 0.05, size=256)}],
                ResourceType.Memory: [mem2, {"b-0": rng.uniform(5e7, 3e8, size=256)}],
            },
        )
        r2 = strategy.run_batch(batch2)
        # a's merged p99 reflects the hot window (way above window-1's rec).
        assert float(r2[0][ResourceType.CPU].request) > float(r1[ResourceType.CPU].request) * 2
        # b exists only in window 2 and still gets a recommendation.
        assert not r2[1][ResourceType.CPU].request.is_nan()

        # The state survives process boundaries (fresh strategy instance).
        strategy2 = TDigestStrategy(TDigestStrategySettings(state_path=path, chunk_size=128))
        store = DigestStore.open_or_create(path, settings.cpu_spec())
        assert sorted(store.keys) == sorted([object_key(obj_a), object_key(obj_b)])
        assert store.cpu_total[store._index[object_key(obj_a)]] == 512  # 2 windows x 256


class TestStoreLocking:
    def test_lock_serializes_concurrent_merges(self, tmp_path):
        import threading
        import time as time_mod

        path = str(tmp_path / "state.npz")
        order = []

        def worker(name: str, hold: float) -> None:
            with DigestStore.locked(path):
                order.append(f"{name}-in")
                store = DigestStore.open_or_create(path, SPEC)
                store.merge_window(
                    [name],
                    np.ones((1, SPEC.num_buckets), np.float32),
                    np.asarray([float(SPEC.num_buckets)], np.float32),
                    np.asarray([1.0], np.float32),
                    np.asarray([1.0], np.float32),
                    np.asarray([1.0], np.float32),
                )
                time_mod.sleep(hold)
                store.save(path)
                order.append(f"{name}-out")

        t1 = threading.Thread(target=worker, args=("a", 0.2))
        t1.start()
        time_mod.sleep(0.05)
        t2 = threading.Thread(target=worker, args=("b", 0.0))
        t2.start()
        t1.join()
        t2.join()
        # Critical sections must not interleave, and both merges must survive.
        assert order in (["a-in", "a-out", "b-in", "b-out"], ["b-in", "b-out", "a-in", "a-out"])
        final = DigestStore.load(path)
        assert sorted(final.keys) == ["a", "b"]

    def test_corrupt_state_error_message(self, tmp_path):
        path = str(tmp_path / "state.npz")
        with open(path, "w") as f:
            f.write("garbage")
        with pytest.raises(ValueError, match="delete the file to start fresh"):
            DigestStore.open_or_create(path, SPEC)


class TestStreamedStatePath:
    def test_host_streamed_window_equals_resident(self, tmp_path, rng, monkeypatch):
        """The state-path window digest built via the host→device chunk
        pipeline must write the same store (bit-identical digests) as the
        resident build."""
        from .test_strategies import force_tiny_stream_threshold

        obj = make_obj("a", ["a-0"])
        batch = window_batch(rng, [obj], t=300)

        resident_path = str(tmp_path / "resident.npz")
        TDigestStrategy(
            TDigestStrategySettings(state_path=resident_path, chunk_size=128, host_stream_mb=-1)
        ).run_batch(batch)

        force_tiny_stream_threshold(monkeypatch)
        streamed_path = str(tmp_path / "streamed.npz")
        TDigestStrategy(
            TDigestStrategySettings(state_path=streamed_path, chunk_size=128, host_stream_mb=0)
        ).run_batch(batch)

        spec = TDigestStrategySettings().cpu_spec()
        a = DigestStore.open_or_create(resident_path, spec)
        b = DigestStore.open_or_create(streamed_path, spec)
        np.testing.assert_array_equal(a.cpu_counts, b.cpu_counts)
        np.testing.assert_array_equal(a.cpu_total, b.cpu_total)
        np.testing.assert_array_equal(a.cpu_peak, b.cpu_peak)
        np.testing.assert_array_equal(a.mem_peak, b.mem_peak)


class TestFoldFleet:
    def test_fold_fleet_matches_manual_merge(self):
        """The delta-window fold entry point (serve scheduler + tdigest
        state_path merge) is exactly merge_window with the keys derived and
        memory peaks converted bytes → MB."""
        from krr_tpu.models.series import DigestedFleet

        spec = DigestSpec(gamma=1.01, min_value=1e-7, num_buckets=64)
        objects = [make_obj("a", ["a-0"]), make_obj("b", ["b-0"])]
        fleet = DigestedFleet.empty(objects, spec.gamma, spec.min_value, spec.num_buckets)
        fleet.merge_cpu_row(0, np.eye(1, 64, 5, dtype=np.float64)[0] * 3, 3.0, 0.4)
        fleet.merge_mem_row(0, 3.0, 2.5e8)  # bytes
        # object b: no data at all (empty digest, -inf peaks)

        store = DigestStore(spec=spec)
        rows = store.fold_fleet(fleet, mem_scale=1e6)
        assert rows.tolist() == [0, 1]
        assert store.keys == [object_key(obj) for obj in objects]
        assert store.cpu_total.tolist() == [3.0, 0.0]
        assert store.mem_peak[0] == np.float32(250.0)  # MB
        assert store.mem_peak[1] == -np.inf  # empty rows stay empty, not NaN

        # Folding a second identical window doubles counts, maxes peaks —
        # and a repeated fold targets the SAME rows.
        rows2 = store.fold_fleet(fleet, mem_scale=1e6)
        assert rows2.tolist() == [0, 1]
        assert store.cpu_total.tolist() == [6.0, 0.0]
        assert store.mem_peak[0] == np.float32(250.0)
