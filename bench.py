"""Benchmark: containers right-sized per second on the available accelerator.

Measures the full fleet recommendation step at the BASELINE.md headline
*workload shape* (10k containers × 7 days of 5-second samples = 120,960
timesteps/container, the config-3 scale) using the production
``simple``-strategy device program: ``fleet_exact`` — **exact** fused-Pallas
bit-space bisection selection over the CPU histories + lane-folded row max
over the memory histories, one dispatch, one readback
(`krr_tpu.ops.pallas_select`). Note this is a stronger result than
BASELINE.md's config-3 row asks for (that row names the approximate tdigest
sketch): the exact kernel turned out faster than the sketch for HBM-resident
data, so the headline metric was renamed from
``containers_per_sec_tdigest_7d_at_5s`` (recorded through 2026-07-29) to
``containers_per_sec_exact_p99_7d_at_5s``. The sketch paths — still the
right tool for streamed/multi-source/incremental data — are timed as
secondary numbers (now Pallas chunk-fold kernels,
`krr_tpu.ops.pallas_sketch`) and carried in the JSON under ``secondary``.

**On-hardware parity gate**: timing alone can hide a TPU-only miscompile, so
after the timed runs this script *asserts on the chip* that (a) the fused
Pallas program returns bit-identical results to the pure-jnp XLA path on a
row subsample, (b) the top-K sketch percentile equals the exact bisection,
and (c) the digest percentile honors its guaranteed relative error bound
with an exact peak. Any mismatch prints the failure, emits
``"parity": "fail"`` and exits nonzero — the headline number is only
reported trustworthy when the gate passes.

Baseline: the reference's algorithm (pure-Python Decimal flatten/sort/index,
`/root/reference/robusta_krr/strategies/simple.py:24-36`) timed on a small
sample and extrapolated per container.

Data is generated on-device in chunks (the bench isolates kernel throughput
from Prometheus-side fetch, which is network-bound; `bench_e2e.py` measures
the fetch+parse+compute pipeline). NOTE: on the tunneled TPU backend
``block_until_ready`` returns early — sync is via small host readbacks.
Prints ONE JSON line:
    {"metric": "containers_per_sec_exact_p99_7d_at_5s_pipelined", "value": N,
     "unit": "containers/s", "vs_baseline": N, "parity": "ok", "runs": N,
     "raw_containers_per_sec": N, "raw_spread_pct": N, "raw_vs_baseline": N,
     "dispatch_floor_ms": N, "pipelined_depth": N, "pipelined_spread_pct": N,
     "floor_corrected_containers_per_sec": N|null, "vs_previous_round": N|null,
     "regression_vs_previous": bool, "fetch_vs_previous_round": N|null,
     "fetch_regression_vs_previous": bool, "secondary": {...}}
The headline ``value`` is the PIPELINED rate (round-4 verdict item 4): R
dispatches, ONE sync — the tunnel RTT amortizes R-fold and the rate converges
to the kernel's own, stable to ~1% across runs, so round-over-round deltas
mean something. The raw single-dispatch rate (~12% spread, rig-RTT-bound) is
carried as ``raw_containers_per_sec``; ``dispatch_floor_ms`` is the measured
trivial jit-call + readback round trip that dominates it, and
``floor_corrected_containers_per_sec`` is the raw measurement with that floor
subtracted (null when the floor comes within 1 ms of the measurement — the
subtraction is meaningless there). ``vs_previous_round`` compares this run's
headline against the newest recorded ``BENCH_r*.json`` stable rate;
``regression_vs_previous`` trips at a >5% drop.

Env knobs: BENCH_CONTAINERS (default 10000), BENCH_TIMESTEPS (default 120960),
BENCH_CHUNK (default 8192), BENCH_RUNS (default 5), BENCH_PIPELINE_DEPTH
(default 16), BENCH_PY_SAMPLE (default 3), BENCH_SKIP_DIGEST,
BENCH_SKIP_E2E, BENCH_PARITY_ROWS (default 512), BENCH_SKIP_JOURNAL,
BENCH_JOURNAL_ROWS (default 2000), BENCH_JOURNAL_TICKS (default 32 — the
history-journal leg: fsync'd append + compaction throughput and a
journal-diff render through the formatter registry, carried under
``secondary.journal_*``), BENCH_SKIP_OBS, BENCH_OBS_ROWS (default 256),
BENCH_OBS_SAMPLES (default 4096), BENCH_OBS_RUNS (default 5 — the
tracing-overhead legs: one identical in-process digest scan with the no-op
vs a recording tracer, gated at <2% wall overhead and bit-exact results,
carried under ``secondary.obs_*``; plus the device-observability leg —
the same ``run_batch`` compute with staged pack/quantile/round sub-spans,
fencing, and padding gauges vs the inert default, same gates, carried
under ``secondary.obs_device_*``), BENCH_SKIP_CHAOS, BENCH_CHAOS_TICKS
(default 8), BENCH_CHAOS_WORKLOADS (default 2 — the chaos soak leg: an
archetype fleet through real serve ticks under a scripted fault timeline,
gated on no crash, recovery bit-exactness vs a never-faulted control, and
a bounded hard-down tick wall, carried under ``secondary.chaos_*``),
BENCH_SKIP_EVAL, BENCH_EVAL_SAMPLES (default 240), BENCH_EVAL_WORKLOADS
(default 2), BENCH_EVAL_TICKS (default 8 — the quality-evaluation leg:
registered strategies + labeled static probes replayed through the real
hysteresis gate over a chaos-archetype fleet, gated on byte-identical
repeated scoreboards and the labeled-archetype ranking contract, replay
throughput carried under ``secondary.eval_*``),
BENCH_SKIP_FETCHPLAN, BENCH_FETCHPLAN_WORKLOADS (default 3 — the adaptive
fetch-engine leg: a real-loader fetch over HTTP where the planner coalesces
AND shards, gated on plan-counter engagement, bit-exactness vs the
``--fetch-plan fixed`` control, and the AIMD autotuner seeing per-query
verdicts, carried under ``secondary.fetchplan_*``), BENCH_SKIP_READPATH,
BENCH_READPATH_WORKLOADS (default 400), BENCH_READPATH_CLIENTS (default 8),
BENCH_READPATH_REQUESTS (default 120 — the read-path loadtest leg:
concurrent keep-alive readers against a live serve during scan ticks,
gated on steady-state cache hit rate, zero-render 304s, pushdown
bit-exactness, LRU bounds, and the cached-vs-uncached RPS ratio, carried
under ``secondary.readpath_*`` with a round-over-round p99 gate in
``readpath_regression_vs_previous``), BENCH_SKIP_HA, BENCH_HA_TICKS
(default 4), BENCH_HA_WORKLOADS (default 2), BENCH_HA_CLIENTS (default 4),
BENCH_HA_REQUESTS (default 40 — the HA/replica leg: a 2-node
consistent-hash ring with a primary|standby aggregator pair, a mid-soak
primary kill plus duplicate injection, and a read replica subscribed to
the epoch feed, gated on merged-view bit-exactness vs the single-process
control, zero lost epochs with exactly-once apply, and replica RPS within
10% of its source, carried under ``secondary.ha_*``), BENCH_SKIP_FLEETOBS,
BENCH_FLEETOBS_TICKS (default 4), BENCH_FLEETOBS_WORKLOADS (default 2 —
the fleet-observability leg: 2 shards + aggregator + read replica each
recording their own trace ring, gated on the cross-process stitched trace
joining scan/apply/install spans, monotone end-to-end freshness lineage
with every stage histogram engaged, and lineage stamping within 2% of the
no-lineage control's tick wall at bit-exact stores, carried under
``secondary.fleet_*``). The
e2e leg runs `bench_e2e.py` in a subprocess with BENCH_E2E_CONTAINERS
defaulted to 10000 (fleet scale) unless already set.

``--smoke``: the same harness at toy scale (tiny fleet, 1 run, e2e legs
included) — a CI-speed end-to-end regression gate, not a measurement. Every
leg still executes (kernels, parity checks, both bench_e2e subprocesses, the
streamed-pipeline fleet leg), so a pipeline break that only shows up
end-to-end fails here in minutes instead of surfacing in the next full bench
round. Explicitly exported BENCH_* values still win over the smoke defaults.
"""

from __future__ import annotations

import json
import os
import sys
import time
from decimal import Decimal


def _time_once(fn) -> float:
    start = time.perf_counter()
    fn()
    return time.perf_counter() - start


def python_reference_seconds_per_container(timesteps: int, sample: int) -> float:
    """Time the reference algorithm (Decimal flatten → percentile-index → max;
    sorted, per its documented intent) on `sample` containers."""
    import numpy as np

    rng = np.random.default_rng(7)
    histories = []
    for _ in range(sample):
        cpu = [Decimal(repr(float(v))) for v in rng.gamma(2.0, 0.05, size=timesteps)]
        mem = [Decimal(repr(float(v))) for v in rng.uniform(1e7, 4e8, size=timesteps)]
        histories.append((cpu, mem))

    start = time.perf_counter()
    for cpu, mem in histories:
        data = sorted(cpu)
        _ = data[int((len(data) - 1) * Decimal(99) / 100)]
        _ = max(mem) * Decimal("1.05")
    return (time.perf_counter() - start) / sample


SMOKE_DEFAULTS = {
    "BENCH_CONTAINERS": "64",
    "BENCH_TIMESTEPS": "1024",
    "BENCH_RUNS": "1",
    "BENCH_PIPELINE_DEPTH": "2",
    "BENCH_PY_SAMPLE": "1",
    "BENCH_PARITY_ROWS": "8",
    # bench_e2e subprocess legs, toy-sized but all EXECUTED — including the
    # full-fleet streamed-pipeline leg (FLEET_ROWS) whose JSON carries
    # fleet_e2e_overlap_pct and the staged-control ratio.
    "BENCH_E2E_CONTAINERS": "8",
    "BENCH_E2E_SAMPLES": "48",
    "BENCH_E2E_INGEST_ROWS": "64",
    "BENCH_E2E_STORE_ROWS": "256",
    "BENCH_E2E_FLEET_ROWS": "12",
    # History-journal leg (host-only): append/compaction throughput plus a
    # diff render through the formatter registry, all EXECUTED at toy scale.
    "BENCH_JOURNAL_ROWS": "32",
    "BENCH_JOURNAL_TICKS": "4",
    # Tracing-overhead leg (host-only): the traced-vs-no-op scan pair still
    # EXECUTES at toy scale (the <2% gate leans on its 10 ms noise floor).
    "BENCH_OBS_ROWS": "48",
    "BENCH_OBS_SAMPLES": "1024",
    "BENCH_OBS_RUNS": "3",
    # Chaos leg: archetype fleet + scripted fault timeline through real
    # serve ticks, at toy scale but with every gate EXECUTED.
    "BENCH_CHAOS_TICKS": "8",
    "BENCH_CHAOS_WORKLOADS": "2",
    # Eval leg: strategy + probe replays over a labeled archetype fleet
    # (determinism + ranking gates EXECUTED at toy scale).
    "BENCH_EVAL_SAMPLES": "96",
    "BENCH_EVAL_WORKLOADS": "1",
    "BENCH_EVAL_TICKS": "6",
    # Discovery leg: watch-reconcile vs per-round relist at equal fleet
    # width with injected churn (bit-exactness + reconcile-beats-relist
    # gates EXECUTED at toy scale).
    "BENCH_DISCOVERY_WORKLOADS": "120",
    "BENCH_DISCOVERY_ROUNDS": "3",
    # Durable-store legs: delta-append vs legacy full rewrite + recovery
    # replay at toy row counts, and the kill-recover-verify soak (real
    # SIGKILLed serve subprocesses) with a reduced kill budget.
    "BENCH_STORE_ROWS": "512",
    "BENCH_STORE_KILLS": "2",
    "BENCH_STORE_KILL_TICKS": "6",
    # Wire leg: compressed + downsampled scan vs the identity/raw control
    # (bit-exactness, engagement, and wire_compression_ratio gates).
    "BENCH_WIRE_WORKLOADS": "2",
    "BENCH_WIRE_SAMPLES": "120",
    # Federation leg: N in-process shards vs the single-process control
    # (merged-store bit-exactness + engagement gates; fold seconds and
    # delta wire bytes trended).
    "BENCH_FED_SHARDS": "3",
    "BENCH_FED_TICKS": "4",
    "BENCH_FED_WORKLOADS": "2",
    # HA leg: 2-node ring (primary|standby pair + single) with a mid-soak
    # primary kill, duplicate injection, and a read replica (bit-exactness,
    # zero-lost-epochs, replica RPS scaling gates), toy-sized.
    "BENCH_HA_TICKS": "4",
    "BENCH_HA_WORKLOADS": "2",
    "BENCH_HA_CLIENTS": "2",
    "BENCH_HA_REQUESTS": "16",
    # Fleet-observability leg: 2 shards + aggregator + replica with every
    # trace ring recording, stitched-trace / lineage-monotonicity /
    # <2%-overhead gates all EXECUTED against the no-lineage control.
    "BENCH_FLEETOBS_TICKS": "3",
    "BENCH_FLEETOBS_WORKLOADS": "2",
    # Read-path leg: concurrent keep-alive readers against a live serve
    # (cache hit rate, 304 zero-render, pushdown bit-exactness, LRU bound,
    # cached-vs-uncached RPS), toy-sized but every gate EXECUTED.
    "BENCH_READPATH_WORKLOADS": "12",
    "BENCH_READPATH_CLIENTS": "4",
    "BENCH_READPATH_REQUESTS": "36",
    # Push-ingest leg: remote-write-fed serve vs the range-fetched pull
    # control (bit-exactness + zero-range-queries + push-beats-pull gates;
    # decode/ingest samples-per-second ceiling trended).
    "BENCH_INGEST_WORKLOADS": "24",
    "BENCH_INGEST_ROUNDS": "3",
}


class KeepAliveReader:
    """Minimal keep-alive HTTP/1.1 client — dependency-free and thin, so
    read-path measurements read the SERVER, not a client library. Shared by
    the readpath and HA legs (the replica-vs-primary RPS comparison must use
    the identical client on both sides)."""

    def __init__(self, port: int):
        self.port = port
        self.reader = self.writer = None

    async def connect(self):
        import asyncio

        self.reader, self.writer = await asyncio.open_connection("127.0.0.1", self.port)

    async def get(self, target: str, headers: "tuple[tuple[str, str], ...]" = ()):
        request = f"GET {target} HTTP/1.1\r\nHost: bench\r\n" + "".join(
            f"{k}: {v}\r\n" for k, v in headers
        ) + "\r\n"
        start = time.perf_counter()
        self.writer.write(request.encode())
        await self.writer.drain()
        status_line = await self.reader.readline()
        status = int(status_line.split()[1])
        response_headers: dict[str, str] = {}
        while True:
            line = await self.reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            name, _, value = line.decode("latin-1").partition(":")
            response_headers[name.strip().lower()] = value.strip()
        length = int(response_headers.get("content-length") or 0)
        body = await self.reader.readexactly(length) if length else b""
        return status, response_headers, body, time.perf_counter() - start

    async def close(self):
        if self.writer is not None:
            self.writer.close()


def journal_leg(secondary: dict) -> None:
    """Journal append/compaction throughput + an end-to-end diff render —
    the history subsystem's secondary numbers (host numpy + disk, no
    accelerator). Appends are fsync'd per tick (the crash-safe contract is
    part of what's being measured); compaction is the atomic whole-file
    rewrite. The diff leg renders the first-vs-last tick delta through the
    json formatter, exercising journal → diff → formatter end to end."""
    import tempfile

    import numpy as np

    from krr_tpu.history.diff import build_diff_result, tick_values
    from krr_tpu.history.journal import RecommendationJournal

    rows = int(os.environ.get("BENCH_JOURNAL_ROWS", 2000))
    ticks = max(2, int(os.environ.get("BENCH_JOURNAL_TICKS", 32)))
    rng = np.random.default_rng(11)
    keys = [f"bench/ns{i % 16}/w{i}/main/Deployment" for i in range(rows)]
    cpu = rng.gamma(2.0, 0.05, rows).astype(np.float32)
    mem = rng.uniform(50, 400, rows).astype(np.float32)
    base_ts = 1_700_000_000.0

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "bench.journal")
        # Retention sized so compaction drops the older half of the ticks.
        journal = RecommendationJournal(path, retention_seconds=(ticks // 2) * 60.0)
        start = time.perf_counter()
        for t in range(ticks):
            published = np.full(rows, t == 0)
            journal.append_tick(base_ts + t * 60.0, keys, cpu * (1 + 0.01 * t), mem, published)
        append_seconds = time.perf_counter() - start
        total = rows * ticks
        secondary["journal_append_records_per_sec"] = round(total / append_seconds, 1)

        before = journal.record_count
        start = time.perf_counter()
        dropped = journal.compact(base_ts + ticks * 60.0)
        compact_seconds = time.perf_counter() - start
        assert dropped > 0, "bench journal compaction dropped nothing — retention sizing bug"
        secondary["journal_compact_records_per_sec"] = round(before / max(compact_seconds, 1e-9), 1)

        # Diff leg over the surviving window: oldest surviving tick vs newest.
        remaining = journal.tick_timestamps()
        start = time.perf_counter()
        diff = build_diff_result(
            tick_values(journal, float(remaining[0])), tick_values(journal, float(remaining[-1]))
        )
        rendered = diff.format("json")
        diff_seconds = time.perf_counter() - start
        assert len(diff.scans) == rows and rendered
        secondary["journal_diff_objects_per_sec"] = round(rows / max(diff_seconds, 1e-9), 1)
        journal.close()
    print(
        f"bench: journal {total} appends {append_seconds:.3f}s "
        f"({total / append_seconds:.0f} rec/s), compaction of {before} recs "
        f"{compact_seconds * 1e3:.1f} ms, diff render {rows} objects {diff_seconds:.3f}s",
        file=sys.stderr,
    )


def chaos_leg(secondary: dict, check) -> None:
    """Chaos soak gates (`tests.fakes.chaos`): an archetype fleet served by
    the REAL composition (real PrometheusLoader over HTTP against the
    fakes) rides a scripted fault timeline — two degraded (partial-outage)
    ticks, one hard-down tick, then recovery. Three gates, all parity-style
    (a failure exits nonzero):

    * no crash — every tick returns (scanned, degraded, or cleanly aborted);
    * recovery bit-exactness — after the faults clear, the soaked resident
      store is BIT-identical to a never-faulted control run's (the degraded
      path's streamed==staged-grade discipline);
    * bounded degraded wall — the hard-down tick's wall stays within an
      absolute ceiling (breaker fail-fast + the retry deadline budget, not
      a full backoff ladder per query).
    """
    import asyncio
    import tempfile

    from krr_tpu.core.config import Config
    from tests.fakes.chaos import (
        ArchetypeSpec,
        FaultSpec,
        FaultTimeline,
        ServerThread,
        build_fleet,
        run_soak,
        stores_bitexact,
        write_kubeconfig,
    )

    ticks = max(8, int(os.environ.get("BENCH_CHAOS_TICKS", 8)))
    workloads = int(os.environ.get("BENCH_CHAOS_WORKLOADS", 2))
    fleet = build_fleet(
        tuple(
            ArchetypeSpec(kind, workloads=workloads, pods=1)
            for kind in ("diurnal", "bursty-batch", "oom-loop", "mixed-qos")
        ),
        samples=240,
        seed=29,
    )
    server = ServerThread(fleet.backend).start()
    try:
        with tempfile.TemporaryDirectory() as tmp:
            kubeconfig = write_kubeconfig(os.path.join(tmp, "kubeconfig"), server.url)

            def config() -> Config:
                return Config(
                    kubeconfig=kubeconfig,
                    prometheus_url=server.url,
                    strategy="tdigest",
                    quiet=True,
                    server_port=0,
                    scan_interval_seconds=300.0,
                    hysteresis_enabled=False,
                    # Ticks run back-to-back in wall time while the scan
                    # clock jumps a cadence: a microscopic cooldown keeps
                    # recovery immediate, the small budget keeps faulted
                    # ticks fast, and the threshold scales with the fleet
                    # knob so one namespace's tail fallback wave (2 ladders
                    # per workload after its healthy siblings finish, which
                    # the success-epoch guard can no longer discount) can't
                    # open the breaker during the PARTIAL phase — only the
                    # hard-down tick (every query failing) trips it.
                    prometheus_breaker_threshold=max(10, 4 * workloads + 2),
                    prometheus_breaker_cooldown_seconds=0.02,
                    prometheus_retry_deadline_seconds=1.0,
                    prometheus_backoff_cap_seconds=0.2,
                    other_args={"history_duration": 1, "timeframe_duration": 1},
                )

            timeline = FaultTimeline(
                [
                    (2, 3, FaultSpec(fail_namespaces=frozenset({"diurnal"}))),
                    (4, 4, FaultSpec(down=True)),
                ]
            )

            async def settle_breaker(server, sample):
                # The breaker cooldown is WALL-clock while soak ticks run
                # back-to-back on a fake scan clock: under CI scheduling
                # jitter the recovery tick's first queries can land inside
                # the cooldown window of the hard-down tick's last
                # fast-fail and quarantine a workload — an extra degraded
                # tick that reads as starvation. Waiting out the cooldown
                # after any tick that left the breaker non-closed makes
                # recovery deterministic: the next tick's first query is
                # the half-open probe.
                if sample.breaker_state and sample.breaker_state > 0:
                    await asyncio.sleep(0.05)

            report = asyncio.run(
                run_soak(
                    config(), fleet.backend, timeline, ticks=ticks,
                    tick_seconds=300.0, on_tick=settle_breaker,
                )
            )
            control = asyncio.run(
                run_soak(
                    config(), fleet.backend, None, ticks=ticks,
                    tick_seconds=300.0, on_tick=settle_breaker,
                )
            )
    finally:
        server.stop()

    counts = report.counts()
    clean_wall = max(t.wall_seconds for t in report.ticks[:2])
    down_wall = report.ticks[4].wall_seconds
    equal, detail = stores_bitexact(report.store, control.store)
    breaker_opens = (
        report.metrics.value(
            "krr_tpu_prom_breaker_transitions_total", cluster="fake", to="open"
        )
        or 0.0
    )
    secondary["chaos_ticks"] = float(len(report.ticks))
    secondary["chaos_degraded_ticks"] = float(counts["degraded"])
    secondary["chaos_aborted_ticks"] = float(counts["aborted"])
    secondary["chaos_clean_tick_seconds"] = round(clean_wall, 4)
    secondary["chaos_down_tick_seconds"] = round(down_wall, 4)
    secondary["chaos_breaker_opens"] = breaker_opens
    secondary["chaos_recovered_bitexact"] = 1.0 if equal else 0.0
    print(
        f"bench: chaos soak {len(report.ticks)} ticks "
        f"({counts['degraded']} degraded, {counts['aborted']} aborted, "
        f"{breaker_opens:.0f} breaker opens): clean tick {clean_wall:.3f}s, "
        f"hard-down tick {down_wall:.3f}s, recovery bit-exact: {equal}",
        file=sys.stderr,
    )
    check(
        "chaos_no_starvation",
        counts["degraded"] == 2 and all(t.ok for t in report.ticks[:4]),
        f"expected 2 degraded published ticks, got {counts}",
    )
    check(
        "chaos_down_tick_aborts",
        report.ticks[4].ok is None and counts["aborted"] == 1,
        f"hard-down tick outcome {report.ticks[4].ok}, counts {counts}",
    )
    check("chaos_recovery_bitexact", equal, detail)
    # Absolute ceiling, generous for CI noise: the budget allows 1 s of
    # backoff and the breaker fail-fasts the rest — without them this tick
    # would burn a retry ladder per query and blow far past it.
    check(
        "chaos_down_tick_wall_bounded",
        down_wall < 10.0,
        f"hard-down tick took {down_wall:.2f}s (clean tick {clean_wall:.2f}s)",
    )


def eval_leg(secondary: dict, check) -> None:
    """Quality-evaluation gates (`krr_tpu.eval`): replay registered
    strategies plus labeled static probes over a chaos-archetype fleet,
    with the archetypes' DECLARED incident windows as ground truth. Two
    parity-style gates:

    * eval_deterministic — the same replay rendered twice is BYTE-identical
      (jitted reductions over fixed shapes, no clock reads anywhere in the
      scoreboard path);
    * eval_ranks_labeled_archetypes — the undersized probe scores >0
      would-have-been OOM incidents on the oom-loop archetype, the
      oversized probe scores none with MORE over-provisioned GB-hours, and
      the board ranks the incident-free probe first (safety before cost).

    Replay wall + throughput are trended under ``secondary.eval_*``.
    """
    import json

    from krr_tpu.eval import (
        StaticReplayStrategy,
        build_scoreboard,
        render_scoreboard,
        replay,
        score_replay,
    )
    from krr_tpu.strategies.base import BaseStrategy
    from tests.fakes.chaos import ArchetypeSpec, build_fleet, fleet_replay_input

    samples = int(os.environ.get("BENCH_EVAL_SAMPLES", 240))
    workloads = int(os.environ.get("BENCH_EVAL_WORKLOADS", 2))
    ticks = int(os.environ.get("BENCH_EVAL_TICKS", 8))
    fleet = build_fleet(
        tuple(
            ArchetypeSpec(kind, workloads=workloads, pods=1)
            for kind in ("oom-loop", "diurnal", "bursty-batch")
        ),
        samples=samples,
        seed=31,
    )
    inputs = fleet_replay_input(fleet)
    probes = (
        # Under every oom-loop incident peak (~7.4e8+ bytes) but over the
        # diurnal baseline; vs comfortably over everything.
        ("static-under", lambda: StaticReplayStrategy(0.01, 3e8)),
        ("static-over", lambda: StaticReplayStrategy(10.0, 5e9)),
    )

    def board_json() -> "tuple[str, float]":
        rows = []
        start = time.perf_counter()
        for name in ("simple", "tdigest"):
            strategy_type = BaseStrategy.find(name)
            strategy = strategy_type(strategy_type.get_settings_type()())
            rows.append(score_replay(inputs, replay(inputs, strategy, name=name, ticks=ticks)))
        wall = time.perf_counter() - start
        for name, make in probes:
            rows.append(score_replay(inputs, replay(inputs, make(), name=name, ticks=ticks)))
        board = build_scoreboard(
            rows,
            samples=len(inputs.timestamps),
            window_seconds=float(inputs.timestamps[-1] - inputs.timestamps[0]),
        )
        return render_scoreboard(board, "json"), wall

    first, wall = board_json()
    second, _ = board_json()
    payload = json.loads(first)
    order = [s["strategy"] for s in payload["scores"]]
    by_name = {s["strategy"]: s for s in payload["scores"]}
    under, over = by_name["static-under"], by_name["static-over"]
    replayed_rows = 2 * len(inputs.keys) * ticks  # registry strategies only
    rows_per_sec = replayed_rows / wall if wall > 0 else 0.0
    secondary["eval_workloads"] = float(len(inputs.keys))
    secondary["eval_samples"] = float(len(inputs.timestamps))
    secondary["eval_replay_seconds"] = round(wall, 4)
    secondary["eval_replay_rows_per_sec"] = round(rows_per_sec, 2)
    print(
        f"bench: eval replayed 2 strategies + {len(probes)} probes over "
        f"{len(inputs.keys)} workloads x {len(inputs.timestamps)} samples "
        f"in {ticks} ticks: {wall:.3f}s ({rows_per_sec:.0f} rows/s), "
        f"board order {order}",
        file=sys.stderr,
    )
    check(
        "eval_deterministic",
        first == second,
        "repeated replay rendered a different scoreboard (byte-identity broken)",
    )
    ranks = (
        under["oom_incidents"] > 0
        and over["oom_incidents"] == 0
        and over["throttle_incidents"] == 0
        and over["overprovisioned_gb_hours"] > under["overprovisioned_gb_hours"]
        and order.index("static-over") < order.index("static-under")
    )
    check(
        "eval_ranks_labeled_archetypes",
        ranks,
        f"under={under['oom_incidents']} oom / {under['overprovisioned_gb_hours']} GBh, "
        f"over={over['oom_incidents']} oom / {over['overprovisioned_gb_hours']} GBh, "
        f"order {order}",
    )


def store_leg(secondary: dict, check) -> None:
    """Durable-store persistence legs (`krr_tpu.core.durastore`), host +
    disk only: the per-tick delta APPEND vs the legacy full-store rewrite
    at the configured row count, and the recovery replay wall. Two
    parity-style gates:

    * delta-beats-rewrite — a tick's ``store_persist_seconds`` (one WAL
      record: sparse window + fsync) must undercut the legacy
      ``store_legacy_save_seconds`` (whole-state atomic rewrite), which is
      the whole point of the WAL;
    * recovery bit-exactness — reopening the directory (checksummed bases
      + WAL replay) reconstructs the persisted state bit-identically.
    """
    import tempfile

    import numpy as np

    from krr_tpu.core.durastore import DurableStore
    from krr_tpu.core.streaming import DigestStore
    from krr_tpu.ops.digest import DigestSpec

    rows = int(os.environ.get("BENCH_STORE_ROWS", 100_000))
    spec = DigestSpec(gamma=1.01, min_value=1e-7, num_buckets=2560)
    rng = np.random.default_rng(23)
    keys = [f"bench/ns{i % 64}/w{i}/main/Deployment" for i in range(rows)]

    def seasoned_store() -> DigestStore:
        """A store with realistic occupancy: ~40 occupied buckets per row
        (a series' samples land in tens of its 2,560 buckets)."""
        store = DigestStore(spec=spec, keys=list(keys))
        occupied = rng.integers(0, spec.num_buckets, size=(rows, 40))
        vals = rng.integers(1, 50, size=(rows, 40)).astype(np.float32)
        flat = occupied + (np.arange(rows)[:, None] * spec.num_buckets)
        np.add.at(store.cpu_counts.ravel(), flat.ravel(), vals.ravel())
        store.cpu_total[:] = store.cpu_counts.sum(axis=1)
        store.cpu_peak[:] = rng.gamma(2.0, 0.3, rows).astype(np.float32)
        store.mem_total[:] = store.cpu_total
        store.mem_peak[:] = rng.uniform(50, 400, rows).astype(np.float32)
        return store

    def tick_window() -> "tuple[np.ndarray, ...]":
        """One delta tick's whole-fleet contribution: every row touched,
        ~4 occupied buckets each (a short window's samples)."""
        counts = np.zeros((rows, spec.num_buckets), np.float32)
        occupied = rng.integers(0, spec.num_buckets, size=(rows, 4))
        np.add.at(
            counts.ravel(),
            (occupied + np.arange(rows)[:, None] * spec.num_buckets).ravel(),
            1.0,
        )
        totals = counts.sum(axis=1)
        return (
            counts,
            totals,
            rng.gamma(2.0, 0.3, rows).astype(np.float32),
            totals,
            rng.uniform(50, 400, rows).astype(np.float32),
        )

    with tempfile.TemporaryDirectory() as tmp:
        # Legacy control: the monolithic atomic rewrite per tick.
        legacy_path = os.path.join(tmp, "legacy.npz")
        legacy = seasoned_store()
        legacy.extra_meta["serve_last_end"] = 1.0
        start = time.perf_counter()
        legacy.save(legacy_path)
        legacy_seconds = time.perf_counter() - start
        legacy_bytes = os.path.getsize(legacy_path)

        # Sharded store, seasoned identically, one delta tick appended.
        state_path = os.path.join(tmp, "state")
        durable = DurableStore.open(state_path, spec)
        durable.store = seasoned_store()
        durable.store.track_deltas = True
        durable.maybe_compact(force=True)  # base snapshots of the seasoned state
        window = tick_window()
        durable.store.merge_window(keys, *window)
        durable.store.extra_meta["serve_last_end"] = 2.0
        start = time.perf_counter()
        durable.save_delta()
        persist_seconds = time.perf_counter() - start
        wal_bytes = durable._wal_size
        final_counts = durable.store.cpu_counts.copy()
        final_extra = dict(durable.store.extra_meta)
        durable.close()

        start = time.perf_counter()
        recovered = DurableStore.open(state_path, spec)
        recovery_seconds = time.perf_counter() - start
        bitexact = bool(
            recovered.store.keys == keys
            and np.array_equal(recovered.store.cpu_counts, final_counts)
            and recovered.store.extra_meta == final_extra
        )
        recovered.close()

    secondary["store_legacy_save_seconds"] = round(legacy_seconds, 4)
    secondary["store_persist_seconds"] = round(persist_seconds, 4)
    secondary["store_recovery_seconds"] = round(recovery_seconds, 4)
    secondary["store_delta_vs_legacy"] = round(legacy_seconds / max(persist_seconds, 1e-9), 1)
    secondary["store_wal_tick_bytes"] = wal_bytes - 8
    print(
        f"bench: durable store {rows} rows: delta append {persist_seconds * 1e3:.1f} ms "
        f"({wal_bytes - 8} B) vs legacy rewrite {legacy_seconds * 1e3:.1f} ms "
        f"({legacy_bytes} B) -> x{legacy_seconds / max(persist_seconds, 1e-9):.1f}; "
        f"recovery {recovery_seconds * 1e3:.1f} ms, bit-exact: {bitexact}",
        file=sys.stderr,
    )
    check(
        "store_delta_beats_full_rewrite",
        persist_seconds < legacy_seconds,
        f"delta append {persist_seconds:.4f}s vs legacy rewrite {legacy_seconds:.4f}s",
    )
    check("store_recovery_bitexact", bitexact, "recovered state differs")


def store_kill_leg(secondary: dict, check) -> None:
    """Kill-recover-verify at toy scale: a REAL serve subprocess over the
    chaos fakes, SIGKILLed at random points (mid-tick, mid-append,
    mid-compaction — the compaction floor is forced tiny), restarted from
    the same state directory, then compared BIT-exact against a
    never-killed control run (`tests.fakes.chaos.run_kill_soak`)."""
    import tempfile

    from krr_tpu.core.durastore import DurableStore
    from krr_tpu.strategies.tdigest import TDigestStrategySettings
    from tests.fakes.chaos import (
        ORIGIN,
        ArchetypeSpec,
        ServerThread,
        build_fleet,
        run_kill_soak,
        stores_bitexact,
        write_kubeconfig,
    )

    kills = int(os.environ.get("BENCH_STORE_KILLS", 2))
    ticks_n = int(os.environ.get("BENCH_STORE_KILL_TICKS", 6))
    fleet = build_fleet(
        (ArchetypeSpec("diurnal", workloads=2, pods=1),
         ArchetypeSpec("oom-loop", workloads=2, pods=1)),
        samples=240,
        seed=31,
    )
    server = ServerThread(fleet.backend).start()
    try:
        with tempfile.TemporaryDirectory() as tmp:
            kubeconfig = write_kubeconfig(os.path.join(tmp, "kubeconfig"), server.url)

            def payload(state_path: str) -> dict:
                return dict(
                    kubeconfig=kubeconfig,
                    prometheus_url=server.url,
                    strategy="tdigest",
                    quiet=True,
                    server_port=0,
                    scan_interval_seconds=300.0,
                    hysteresis_enabled=False,
                    store_compact_min_wal_mb=0.002,
                    prometheus_retry_deadline_seconds=1.0,
                    prometheus_backoff_cap_seconds=0.2,
                    other_args={
                        "history_duration": 1,
                        "timeframe_duration": 1,
                        "state_path": state_path,
                    },
                )

            ticks = [ORIGIN + 3600.0 + i * 300.0 for i in range(ticks_n)]
            repo = os.path.dirname(os.path.abspath(__file__))
            state = os.path.join(tmp, "state")
            control = os.path.join(tmp, "control")
            start = time.perf_counter()
            report = run_kill_soak(
                payload(state), ticks, kills=kills, seed=41,
                cfg_path=os.path.join(tmp, "soak.json"), repo_root=repo,
                env={**os.environ},
            )
            run_kill_soak(
                payload(control), ticks, kills=0, seed=42,
                cfg_path=os.path.join(tmp, "control.json"), repo_root=repo,
                env={**os.environ},
            )
            wall = time.perf_counter() - start
            spec = TDigestStrategySettings().cpu_spec()
            soaked = DurableStore.open(state, spec)
            clean = DurableStore.open(control, spec)
            equal, detail = stores_bitexact(soaked.store, clean.store)
            cursor_equal = (
                soaked.store.extra_meta.get("serve_last_end")
                == clean.store.extra_meta.get("serve_last_end")
            )
            soaked.close()
            clean.close()
    finally:
        server.stop()

    secondary["store_kill_recover_bitexact"] = 1.0 if (equal and cursor_equal) else 0.0
    secondary["store_kill_runs"] = float(report["runs"])
    secondary["store_kills"] = float(report["kills"])
    print(
        f"bench: kill-recover soak {report['kills']} SIGKILLs over {ticks_n} ticks "
        f"({report['runs']} runs, {wall:.1f}s): bit-exact vs control: {equal and cursor_equal}",
        file=sys.stderr,
    )
    check(
        "store_kill_recover_bitexact",
        equal and cursor_equal,
        detail if not equal else "window cursor differs",
    )


def discovery_leg(secondary: dict, check) -> None:
    """Watch-driven discovery gates (`--discovery-mode watch`): at the same
    fleet width, with the same injected churn per round, the watch
    reconcile must (a) stay BIT-identical — objects and staged order — to a
    fresh relist at every round, and (b) beat the relist's wall (the whole
    point of an O(churn) resident inventory is that the per-tick discovery
    cost stops scaling with the fleet). Trended as ``secondary.discovery_*``.
    """
    import asyncio
    import statistics
    import tempfile
    import time as _time

    from krr_tpu.core.config import Config
    from krr_tpu.integrations.kubernetes import KubernetesLoader
    from tests.fakes.chaos import write_kubeconfig
    from tests.fakes.servers import FakeBackend, FakeCluster, FakeMetrics, ServerThread

    workloads = int(os.environ.get("BENCH_DISCOVERY_WORKLOADS", 400))
    rounds = max(2, int(os.environ.get("BENCH_DISCOVERY_ROUNDS", 5)))
    namespaces = max(2, min(8, workloads // 20))
    churn = max(1, workloads // 50)

    cluster = FakeCluster()
    created: "list[tuple[str, str]]" = []  # (name, namespace), oldest first
    serial = [0]

    def add_one() -> None:
        namespace = f"ns-{serial[0] % namespaces}"
        name = f"wl-{serial[0]}"
        serial[0] += 1
        cluster.add_workload_with_pods("Deployment", name, namespace, pod_count=2)
        created.append((name, namespace))

    def drop_one() -> None:
        name, namespace = created.pop(0)
        cluster.delete_workload("Deployment", name, namespace)
        cluster.delete_pod(f"{name}-0", namespace)
        cluster.delete_pod(f"{name}-1", namespace)

    for _ in range(workloads):
        add_one()

    backend = FakeBackend(cluster, FakeMetrics())
    server = ServerThread(backend).start()
    try:
        with tempfile.TemporaryDirectory() as tmp:
            kubeconfig = write_kubeconfig(os.path.join(tmp, "kubeconfig"), server.url)

            def config(**overrides) -> Config:
                return Config(kubeconfig=kubeconfig, quiet=True, **overrides)

            async def run() -> dict:
                watch = KubernetesLoader(
                    config(
                        discovery_mode="watch",
                        # The verify audit stays out of the measurement: the
                        # reconcile path itself is what's on the clock.
                        discovery_verify_interval_seconds=3600.0,
                    )
                )
                relist = KubernetesLoader(config())
                relist_walls: list[float] = []
                reconcile_walls: list[float] = []
                bitexact = True
                try:
                    await watch.list_scannable_objects(["fake"])  # cold seed
                    for _round in range(rounds):
                        for _ in range(churn):
                            drop_one()
                            add_one()
                        t0 = _time.perf_counter()
                        relisted = await relist.list_scannable_objects(["fake"])
                        relist_walls.append(_time.perf_counter() - t0)
                        expected = [obj.model_dump() for obj in relisted]
                        # Wait for watch delivery OUTSIDE the timed window —
                        # the reconcile being measured is the steady-state
                        # tick cost, not event-propagation latency.
                        deadline = _time.monotonic() + 30.0
                        while _time.monotonic() < deadline:
                            watched = await watch.list_scannable_objects(["fake"])
                            if [obj.model_dump() for obj in watched] == expected:
                                break
                            await asyncio.sleep(0.02)
                        t0 = _time.perf_counter()
                        watched = await watch.list_scannable_objects(["fake"])
                        reconcile_walls.append(_time.perf_counter() - t0)
                        bitexact = bitexact and (
                            [obj.model_dump() for obj in watched] == expected
                        )
                finally:
                    await watch.close()
                    await relist.close()
                return {
                    "relist_seconds": statistics.median(relist_walls),
                    "reconcile_seconds": statistics.median(reconcile_walls),
                    "bitexact": bitexact,
                    "objects": len(created) * 1,
                }

            report = asyncio.run(run())
    finally:
        server.stop()

    relist_seconds = report["relist_seconds"]
    reconcile_seconds = report["reconcile_seconds"]
    check(
        "discovery_bitexact",
        report["bitexact"],
        "watch reconcile diverged from the fresh relist",
    )
    check(
        "discovery_reconcile_beats_relist",
        reconcile_seconds < relist_seconds,
        f"reconcile {reconcile_seconds:.4f}s vs relist {relist_seconds:.4f}s",
    )
    secondary["discovery_workloads"] = float(workloads)
    secondary["discovery_churn_per_round"] = float(churn)
    secondary["discovery_relist_seconds"] = round(relist_seconds, 4)
    secondary["discovery_reconcile_seconds"] = round(reconcile_seconds, 4)
    secondary["discovery_speedup"] = round(relist_seconds / max(reconcile_seconds, 1e-9), 1)
    secondary["discovery_bitexact"] = 1.0 if report["bitexact"] else 0.0
    secondary["discovery_reconcile_beats_relist"] = (
        1.0 if reconcile_seconds < relist_seconds else 0.0
    )
    print(
        f"bench: discovery leg {workloads} workloads x {rounds} rounds "
        f"(churn {churn}/round): reconcile {reconcile_seconds * 1e3:.1f}ms vs "
        f"relist {relist_seconds * 1e3:.1f}ms "
        f"({secondary['discovery_speedup']}x), bitexact={report['bitexact']}",
        file=sys.stderr,
    )


def ingest_leg(secondary: dict, check) -> None:
    """Push-ingest gates (`--metrics-mode push`, `krr_tpu.ingest`): a
    remote-write-fed serve and a range-fetched pull control run the same
    fleet over byte-identical fake series. Three parity-style gates:

    * every round's published result AND the resident digest store stay
      BIT-identical between the push and pull stacks (the audit's contract,
      measured end to end);
    * steady-state push ticks (after the first round's verify audit) issue
      ZERO range queries — pinned on the fake Prometheus request counter;
    * the push tick wall beats the range-fetched control's (the point of
      folding buffered samples instead of re-fetching windows).

    The decode+route+buffer ceiling (samples/s through ``ingest_body``) is
    trended as ``secondary.ingest_samples_per_second``.
    """
    import asyncio
    import statistics
    import tempfile
    import time as _time

    import numpy as np

    from krr_tpu.core.config import Config
    from krr_tpu.ingest import IngestPlane
    from krr_tpu.server.app import KrrServer
    from tests.fakes.chaos import write_kubeconfig
    from tests.fakes.remote_write import RemoteWriteSender
    from tests.fakes.servers import FakeBackend, FakeCluster, FakeMetrics, ServerThread

    workloads = int(os.environ.get("BENCH_INGEST_WORKLOADS", 200))
    rounds = max(2, int(os.environ.get("BENCH_INGEST_ROUNDS", 5)))
    series_len = max(180, 62 + rounds * 10)
    origin = FakeBackend.SERIES_ORIGIN

    def build_env(series: dict):
        cluster = FakeCluster()
        metrics = FakeMetrics()
        metrics.enforce_range = True
        for i in range(workloads):
            namespace = f"ns-{i % 8}"
            for pod in cluster.add_workload_with_pods(
                "Deployment", f"wl-{i}", namespace, pod_count=2
            ):
                cpu, mem = series[(namespace, pod)]
                metrics.set_series(namespace, "main", pod, cpu=cpu, memory=mem)
        return cluster, metrics

    rng = np.random.default_rng(77)
    series = {}
    for i in range(workloads):
        namespace = f"ns-{i % 8}"
        for p in range(2):
            series[(namespace, f"wl-{i}-{p}")] = (
                rng.gamma(2.0, 0.05, series_len),
                rng.uniform(5e7, 4e8, series_len),
            )
    push_cluster, push_metrics = build_env(series)
    pull_cluster, pull_metrics = build_env(series)
    push_server = ServerThread(FakeBackend(push_cluster, push_metrics)).start()
    pull_server = ServerThread(FakeBackend(pull_cluster, pull_metrics)).start()

    try:
        with tempfile.TemporaryDirectory() as tmp:
            push_kube = write_kubeconfig(os.path.join(tmp, "kube-push"), push_server.url)
            pull_kube = write_kubeconfig(os.path.join(tmp, "kube-pull"), pull_server.url)

            def config(kubeconfig, prometheus_url, **overrides) -> Config:
                return Config(
                    kubeconfig=kubeconfig, prometheus_url=prometheus_url,
                    strategy="tdigest", quiet=True, server_port=0,
                    hysteresis_enabled=False,
                    prometheus_breaker_cooldown_seconds=0.02,
                    other_args={"history_duration": 1, "timeframe_duration": 1},
                    **overrides,
                )

            async def run() -> dict:
                now = [origin + 3600.0]
                push_ks = KrrServer(
                    config(
                        push_kube, push_server.url,
                        metrics_mode="push", ingest_port=0,
                        # One verify round (the first push tick: the audit's
                        # range control is part of the contract), then pure
                        # push — the zero-query regime under measurement.
                        ingest_verify_interval_seconds=1e9,
                    ),
                    clock=lambda: now[0],
                )
                pull_ks = KrrServer(
                    config(pull_kube, pull_server.url), clock=lambda: now[0]
                )
                await push_ks.start(run_scheduler=False)
                await pull_ks.start(run_scheduler=False)
                try:
                    sender = RemoteWriteSender(push_metrics)
                    ingest_port = push_ks.ingest_listener.port
                    assert await push_ks.scheduler.tick()
                    assert await pull_ks.scheduler.tick()
                    push_walls: list[float] = []
                    pull_walls: list[float] = []
                    bitexact = True
                    steady_requests = 0
                    for r in range(1, rounds + 1):
                        now[0] = origin + 3600.0 + 600.0 * r
                        i0, i1 = 61 + (r - 1) * 10, 60 + r * 10
                        status = await sender.push(ingest_port, i0, i1)
                        assert status == 204, f"push round {r}: HTTP {status}"
                        requests_before = push_metrics.request_count
                        t0 = _time.perf_counter()
                        assert await push_ks.scheduler.tick()
                        push_walls.append(_time.perf_counter() - t0)
                        if r > 1:  # round 1 runs the verify audit's fetch
                            steady_requests += push_metrics.request_count - requests_before
                        t0 = _time.perf_counter()
                        assert await pull_ks.scheduler.tick()
                        pull_walls.append(_time.perf_counter() - t0)
                        bitexact = bitexact and (
                            push_ks.state.peek().result.format("json")
                            == pull_ks.state.peek().result.format("json")
                        )
                    store_equal = all(
                        np.array_equal(getattr(push_ks.state.store, field),
                                       getattr(pull_ks.state.store, field))
                        for field in ("cpu_counts", "cpu_total", "cpu_peak",
                                      "mem_total", "mem_peak")
                    )
                    ingest_stats = push_ks.ingest.stats()
                    return {
                        "push_seconds": statistics.median(push_walls),
                        "pull_seconds": statistics.median(pull_walls),
                        "bitexact": bitexact and store_equal,
                        "steady_requests": steady_requests,
                        "rejected": sum(ingest_stats["rejected"].values()),
                    }
                finally:
                    await push_ks.shutdown()
                    await pull_ks.shutdown()

            report = asyncio.run(run())
    finally:
        push_server.stop()
        pull_server.stop()

    # Decode+route+buffer ceiling, off the serve path: successive window
    # bodies through a fresh plane, wall-clocked end to end.
    plane = IngestPlane(max_samples_per_series=1 << 20)
    sender = RemoteWriteSender(push_metrics)
    chunk = 30
    bodies = [
        sender.frames(i, min(i + chunk - 1, series_len - 1))
        for i in range(0, series_len, chunk)
    ]
    t0 = _time.perf_counter()
    accepted = sum(plane.ingest_body(body) for body in bodies)
    ingest_wall = _time.perf_counter() - t0
    samples_per_second = accepted / max(ingest_wall, 1e-9)

    check("push_ingest_bitexact", report["bitexact"], "push stack diverged from pull control")
    check(
        "push_zero_range_queries",
        report["steady_requests"] == 0,
        f"{report['steady_requests']} range queries during steady-state push ticks",
    )
    check(
        "push_tick_beats_pull",
        report["push_seconds"] < report["pull_seconds"],
        f"push {report['push_seconds']:.4f}s vs pull {report['pull_seconds']:.4f}s",
    )
    secondary["ingest_workloads"] = float(workloads)
    secondary["ingest_rounds"] = float(rounds)
    secondary["ingest_push_tick_seconds"] = round(report["push_seconds"], 4)
    secondary["ingest_pull_tick_seconds"] = round(report["pull_seconds"], 4)
    secondary["ingest_tick_speedup"] = round(
        report["pull_seconds"] / max(report["push_seconds"], 1e-9), 1
    )
    secondary["ingest_samples_per_second"] = round(samples_per_second)
    secondary["ingest_bitexact"] = 1.0 if report["bitexact"] else 0.0
    secondary["ingest_zero_range_queries"] = 1.0 if report["steady_requests"] == 0 else 0.0
    secondary["ingest_rejected_samples"] = float(report["rejected"])
    print(
        f"bench: ingest leg {workloads} workloads x {rounds} rounds: push tick "
        f"{report['push_seconds'] * 1e3:.1f}ms vs pull {report['pull_seconds'] * 1e3:.1f}ms "
        f"({secondary['ingest_tick_speedup']}x), decode ceiling "
        f"{samples_per_second / 1e6:.2f}M samples/s, bitexact={report['bitexact']}",
        file=sys.stderr,
    )


def fetchplan_leg(secondary: dict, check) -> None:
    """Adaptive fetch-engine gates (`krr_tpu.core.fetchplan` + the
    prometheus loader's plan/pump/limiter wiring), at toy scale with every
    gate EXECUTED: a fleet shaped so BOTH planner transforms fire (one
    giant namespace shards, three small ones coalesce) is fetched through
    the real PrometheusLoader over HTTP twice — adaptive plan vs the
    ``--fetch-plan fixed`` escape-hatch control. Three parity-style gates:

    * engagement — the plan counters are non-zero (coalesced >= 1 query
      group, sharded >= 2) so a planner wiring break can't pass silently;
    * bit-exactness — the adaptive fleet digest arrays are BIT-identical
      to the fixed-plan control's;
    * autotuner — the AIMD limiter saw per-query TTFB verdicts and
      exported its live in-flight limit gauge.
    """
    import asyncio

    import numpy as np

    from krr_tpu.core.config import Config
    from krr_tpu.integrations.kubernetes import KubernetesLoader
    from krr_tpu.integrations.prometheus import PrometheusLoader
    from krr_tpu.obs.metrics import MetricsRegistry
    from tests.fakes.chaos import write_kubeconfig
    from tests.fakes.servers import FakeBackend, FakeCluster, FakeMetrics, ServerThread

    workloads = int(os.environ.get("BENCH_FETCHPLAN_WORKLOADS", 3))
    cluster = FakeCluster()
    metrics = FakeMetrics()
    rng = np.random.default_rng(31)

    def add(namespace: str, name: str, pod_count: int) -> None:
        for pod in cluster.add_workload_with_pods(
            "Deployment", name, namespace, pod_count=pod_count
        ):
            metrics.set_series(
                namespace, "main", pod,
                cpu=rng.gamma(2.0, 0.05, 48), memory=rng.uniform(5e7, 4e8, 48),
            )

    for w in range(workloads):
        add("big", f"bigwl-{w}", pod_count=4)
    for ns in ("s1", "s2", "s3"):
        add(ns, f"{ns}-app", pod_count=1)

    server = ServerThread(FakeBackend(cluster, metrics)).start()
    try:
        import tempfile

        with tempfile.TemporaryDirectory() as tmp:
            kubeconfig = write_kubeconfig(os.path.join(tmp, "kubeconfig"), server.url)

            def config(**overrides) -> Config:
                return Config(
                    kubeconfig=kubeconfig,
                    prometheus_url=server.url,
                    quiet=True,
                    # Tiny plan targets so the toy fleet exercises BOTH
                    # transforms (sharding needs >= 2x this many series).
                    fetch_plan_target_series=6,
                    **overrides,
                )

            async def discover_once():
                loader = KubernetesLoader(config())
                try:
                    return await loader.list_scannable_objects(["fake"])
                finally:
                    await loader.close()  # pooled clients outlive calls now

            objects = asyncio.run(discover_once())

            def gather(cfg, registry=None):
                async def fetch():
                    prom = PrometheusLoader(cfg, cluster="fake", metrics=registry)
                    try:
                        fleet = await prom.gather_fleet_digests(
                            objects, 3600, 60, gamma=1.01, min_value=1e-7, num_buckets=128
                        )
                        return fleet, prom._limiter
                    finally:
                        await prom.close()

                return asyncio.run(fetch())

            registry = MetricsRegistry()
            start = time.perf_counter()
            adaptive, limiter = gather(config(), registry)
            adaptive_seconds = time.perf_counter() - start
            fixed, _ = gather(config(fetch_plan="fixed"))
    finally:
        server.stop()

    coalesced = registry.total("krr_tpu_fetch_plan_coalesced_total")
    sharded = registry.total("krr_tpu_fetch_plan_sharded_total")
    bitexact = all(
        np.array_equal(getattr(adaptive, attr), getattr(fixed, attr))
        for attr in ("cpu_counts", "cpu_total", "cpu_peak", "mem_total", "mem_peak")
    )
    limit_gauge = registry.value("krr_tpu_prom_inflight_limit", cluster="fake")
    autotuned = limiter.enabled and limiter.baseline_ttfb is not None and limit_gauge
    secondary["fetchplan_scan_seconds"] = round(adaptive_seconds, 4)
    secondary["fetchplan_coalesced"] = coalesced
    secondary["fetchplan_sharded"] = sharded
    secondary["fetchplan_bitexact"] = 1.0 if bitexact else 0.0
    secondary["fetchplan_autotune_engaged"] = 1.0 if autotuned else 0.0
    print(
        f"bench: fetchplan {len(objects)} workloads in {adaptive_seconds:.3f}s "
        f"({coalesced:.0f} coalesced + {sharded:.0f} sharded groups, "
        f"inflight limit {limit_gauge}, bit-exact vs fixed plan: {bitexact})",
        file=sys.stderr,
    )
    check(
        "fetchplan_engaged",
        coalesced >= 1 and sharded >= 2,
        f"plan counters coalesced={coalesced} sharded={sharded}",
    )
    check("fetchplan_bitexact", bitexact, "adaptive plan diverged from the fixed plan")
    check(
        "fetchplan_autotuner",
        bool(autotuned),
        f"limiter enabled={limiter.enabled} baseline={limiter.baseline_ttfb} gauge={limit_gauge}",
    )


def wire_leg(secondary: dict, check) -> None:
    """Wire-shrink gates (compressed transport + server-side downsampling,
    `--fetch-compression`/`--fetch-downsample`): the same grid-aligned
    digest-fleet fetch runs through the real PrometheusLoader over HTTP
    twice — treated (gzip negotiation + downsampled stats route) vs the
    identity/raw escape-hatch control. Three parity-style gates:

    * bit-exactness — the treated fleet arrays are BIT-identical to the
      identity/raw control's;
    * engagement — gzip responses negotiated AND stats queries rode the
      downsample rewrite (a wiring break can't pass silently);
    * compression — wire bytes shrank: ``wire_compression_ratio``
      (identity wire ÷ treated wire) must hit the acceptance bar of 5x.
      The ratio is deterministic for a fixed fixture (byte counts, not
      timings), so the gate cannot flake.
    """
    import asyncio

    import numpy as np

    from krr_tpu.core.config import Config
    from krr_tpu.integrations.kubernetes import KubernetesLoader
    from krr_tpu.integrations.prometheus import PrometheusLoader
    from krr_tpu.obs.metrics import MetricsRegistry
    from tests.fakes.chaos import write_kubeconfig
    from tests.fakes.servers import FakeBackend, FakeCluster, FakeMetrics, ServerThread

    workloads = int(os.environ.get("BENCH_WIRE_WORKLOADS", 3))
    samples = int(os.environ.get("BENCH_WIRE_SAMPLES", 180))
    cluster = FakeCluster()
    metrics = FakeMetrics()
    metrics.enforce_range = True
    rng = np.random.default_rng(43)
    for ns in ("w1", "w2"):
        for w in range(workloads):
            for pod in cluster.add_workload_with_pods(
                "Deployment", f"{ns}-wl{w}", ns, pod_count=2
            ):
                # Realistic value precision (real fleets quantize: irates
                # resolve to ~0.1 millicores, working sets to whole pages)
                # — full-precision iid random mantissas would render the
                # JSON artificially incompressible and benchmark the RNG's
                # entropy instead of the transport.
                metrics.set_series(
                    ns, "main", pod,
                    cpu=np.round(rng.gamma(2.0, 0.05, samples), 4),
                    memory=np.floor(rng.uniform(5e7, 4e8, samples) / 4096) * 4096,
                )

    backend = FakeBackend(cluster, metrics)
    # Sample anchor on the absolute minute grid: downsample eligibility
    # (epoch-aligned subquery steps) and the fake's interval-membership
    # sample model both demand it.
    backend.SERIES_ORIGIN = 1_699_999_980.0
    start = backend.SERIES_ORIGIN
    end = start + (samples - 1) * 60.0
    server = ServerThread(backend).start()
    try:
        import tempfile

        with tempfile.TemporaryDirectory() as tmp:
            kubeconfig = write_kubeconfig(os.path.join(tmp, "kubeconfig"), server.url)

            def config(**overrides) -> Config:
                return Config(
                    kubeconfig=kubeconfig,
                    prometheus_url=server.url,
                    quiet=True,
                    **overrides,
                )

            async def discover_once():
                loader = KubernetesLoader(config())
                try:
                    return await loader.list_scannable_objects(["fake"])
                finally:
                    await loader.close()  # pooled clients outlive calls now

            objects = asyncio.run(discover_once())

            def gather(cfg, registry):
                async def fetch():
                    prom = PrometheusLoader(cfg, cluster="fake", metrics=registry)
                    try:
                        return await prom.gather_fleet_digests(
                            objects, end - start, 60, gamma=1.01, min_value=1e-7,
                            num_buckets=128, end_time=end,
                        )
                    finally:
                        await prom.close()

                return asyncio.run(fetch())

            treated_registry = MetricsRegistry()
            t0 = time.perf_counter()
            treated = gather(config(fetch_downsample="auto"), treated_registry)
            treated_seconds = time.perf_counter() - t0
            control_registry = MetricsRegistry()
            control = gather(
                config(fetch_compression="off", fetch_downsample="off"),
                control_registry,
            )
    finally:
        server.stop()

    bitexact = all(
        np.array_equal(getattr(treated, attr), getattr(control, attr))
        for attr in ("cpu_counts", "cpu_total", "cpu_peak", "mem_total", "mem_peak")
    ) and not treated.failed_rows
    treated_wire = treated_registry.total("krr_tpu_prom_wire_bytes_total")
    control_wire = control_registry.total("krr_tpu_prom_wire_bytes_total")
    gzip_responses = treated_registry.value(
        "krr_tpu_prom_wire_encoding_total", encoding="gzip"
    ) or 0.0
    downsampled = treated_registry.value(
        "krr_tpu_fetch_downsampled_total", cluster="fake"
    ) or 0.0
    ratio = control_wire / treated_wire if treated_wire else 0.0
    secondary["wire_scan_seconds"] = round(treated_seconds, 4)
    secondary["wire_identity_mb"] = round(control_wire / 1e6, 3)
    secondary["wire_compressed_mb"] = round(treated_wire / 1e6, 3)
    secondary["wire_compression_ratio"] = round(ratio, 2)
    secondary["wire_gzip_responses"] = gzip_responses
    secondary["wire_downsampled_queries"] = downsampled
    secondary["wire_bitexact"] = 1.0 if bitexact else 0.0
    print(
        f"bench: wire {len(objects)} workloads x {samples} samples -> "
        f"{control_wire / 1e6:.2f} MB identity vs {treated_wire / 1e6:.2f} MB "
        f"treated (x{ratio:.1f}, {gzip_responses:.0f} gzip responses, "
        f"{downsampled:.0f} downsampled queries, bit-exact: {bitexact})",
        file=sys.stderr,
    )
    check("wire_bitexact", bitexact, "treated scan diverged from the identity/raw control")
    check(
        "wire_engaged",
        gzip_responses >= 1 and downsampled >= 1,
        f"gzip={gzip_responses} downsampled={downsampled}",
    )
    check(
        "wire_ratio",
        ratio >= 5.0,
        f"wire_compression_ratio {ratio:.2f} < 5 "
        f"(identity {control_wire}B vs treated {treated_wire}B)",
    )


def federation_leg(secondary: dict, check) -> None:
    """Federation gates (`krr_tpu.federation`): N in-process scanner shards
    stream their ticks' delta-WAL records over real TCP to an aggregator
    serve, against a single-process control scanning the same fleet. Two
    parity-style gates:

    * bit-exactness — the aggregator's merged DigestStore is bit-identical
      (per key) to the single-process control's after every tick applies;
    * engagement — every shard connected, records actually flowed, and the
      aggregate ticks applied them (a silently idle federation must fail,
      not trend zeros).

    Trended: ``federation_fold_seconds`` (aggregate-tick replay cost, the
    sum of the apply histogram) and ``federation_wire_bytes`` (delta record
    payload bytes on the wire per run), under ``secondary.federation_*``.
    """
    import asyncio
    import time as _time

    from krr_tpu.core.runner import ScanSession
    from krr_tpu.core.config import Config
    from krr_tpu.federation.shard import FederatedShard
    from krr_tpu.server.app import KrrServer
    from tests.fakes.federation import (
        FleetInventory,
        MultiClusterFleet,
        ORIGIN,
        history_factory,
        stores_bitexact_by_key,
    )

    shards_n = max(2, int(os.environ.get("BENCH_FED_SHARDS", 3)))
    ticks = max(2, int(os.environ.get("BENCH_FED_TICKS", 4)))
    workloads = max(1, int(os.environ.get("BENCH_FED_WORKLOADS", 2)))
    tick_seconds = 300.0
    start = ORIGIN + 3600.0
    fleet = MultiClusterFleet(
        clusters=shards_n,
        namespaces_per_cluster=2,
        workloads_per_namespace=workloads,
        seed=53,
    )

    def config(**overrides) -> Config:
        defaults = dict(
            strategy="tdigest",
            quiet=True,
            server_port=0,
            scan_interval_seconds=tick_seconds,
            hysteresis_enabled=False,
            other_args={"history_duration": 1, "timeframe_duration": 1},
        )
        defaults.update(overrides)
        return Config(**defaults)

    async def run() -> dict:
        now = [start]

        # Single-process control over the whole fleet.
        control = KrrServer(
            config(),
            session=ScanSession(
                config(),
                inventory=FleetInventory(fleet),
                history_factory=history_factory(fleet),
            ),
            clock=lambda: now[0],
        )
        for t in range(ticks):
            now[0] = start + t * tick_seconds
            assert await control.scheduler.run_once()

        # Federated: aggregator serve + one in-process shard per cluster,
        # over real TCP.
        now[0] = start
        server = KrrServer(
            config(federation_listen="127.0.0.1:0"),
            session=ScanSession(
                config(),
                inventory=FleetInventory(fleet, clusters=[]),
                history_factory=history_factory(fleet),
            ),
            clock=lambda: now[0],
        )
        await server.start(run_scheduler=False)
        shards = [
            FederatedShard(
                config(
                    clusters=[c],
                    federation_aggregator=f"127.0.0.1:{server.aggregator.port}",
                ),
                session=ScanSession(
                    config(clusters=[c]),
                    inventory=FleetInventory(fleet, clusters=[c]),
                    history_factory=history_factory(fleet),
                ),
                clock=lambda: now[0],
                shard_id=c,
            )
            for c in fleet.clusters
        ]
        try:
            for t in range(ticks):
                now[0] = start + t * tick_seconds
                for shard in shards:
                    assert await shard.tick(now[0])
                agg = server.aggregator
                deadline = _time.monotonic() + 30.0
                while not all(
                    s.shard_id in agg._shards
                    and agg._shards[s.shard_id].enqueued >= s.epoch
                    for s in shards
                ):
                    assert _time.monotonic() < deadline, "aggregator never received"
                    await asyncio.sleep(0.01)
                assert await server.scheduler.run_once()
                for shard in shards:
                    assert await shard.wait_acked(shard.epoch, timeout=10.0)
            metrics = server.state.metrics
            equal, detail = stores_bitexact_by_key(
                server.state.store, control.state.store
            )
            return {
                "equal": equal,
                "detail": detail,
                "connected": metrics.value("krr_tpu_federation_connected_shards") or 0.0,
                "records": metrics.total("krr_tpu_federation_records_total"),
                "wire_bytes": metrics.total("krr_tpu_federation_bytes_total"),
                "fold_seconds": metrics.total("krr_tpu_federation_apply_seconds_sum"),
                "applied": sum(s.applied for s in agg._shards.values()),
                "rows": len(server.state.store.keys),
            }
        finally:
            for shard in shards:
                await shard.close()
            await server.shutdown()
            await control.shutdown()

    report = asyncio.run(run())
    secondary["federation_shards"] = float(shards_n)
    secondary["federation_ticks"] = float(ticks)
    secondary["federation_rows"] = float(report["rows"])
    secondary["federation_records"] = report["records"]
    secondary["federation_wire_bytes"] = report["wire_bytes"]
    secondary["federation_fold_seconds"] = round(report["fold_seconds"], 4)
    secondary["federation_bitexact"] = 1.0 if report["equal"] else 0.0
    print(
        f"bench: federation {shards_n} shards x {ticks} ticks -> "
        f"{report['records']:.0f} records / {report['wire_bytes'] / 1e3:.1f} KB wire, "
        f"aggregate fold {report['fold_seconds']:.4f}s, "
        f"merged store bit-exact: {report['equal']}",
        file=sys.stderr,
    )
    check("federation_bitexact", report["equal"], report["detail"])
    check(
        "federation_engaged",
        report["connected"] == shards_n
        and report["records"] >= shards_n * ticks
        and report["applied"] >= shards_n * ticks
        and report["wire_bytes"] > 0,
        f"connected={report['connected']}, records={report['records']}, "
        f"applied={report['applied']}, wire={report['wire_bytes']}",
    )



def ha_leg(secondary: dict, check) -> None:
    """HA aggregation + read-replica gates (`krr_tpu.federation.ring` /
    `krr_tpu.federation.replica`): a 2-node consistent-hash ring — node
    ``a0`` an HA primary|standby pair sharing the replicated delta-WAL
    stream, node ``a1`` a single aggregator — fed by one shard per
    cluster, plus one stateless read replica subscribed to ``a1``'s epoch
    feed. The soak kills ``a0``'s primary mid-run and force-feeds the
    standby a duplicate record (disconnect after enqueue, before the
    aggregate tick acks) to exercise the exactly-once watermark. Gates:

    * ``ha_bitexact`` — the union of the surviving aggregators' stores
      and served response scans is bit-identical, per key, to a
      single-process control over the same fleet;
    * ``ha_failover_zero_lost_epochs`` — after the kill, every shard
      epoch is acked and applied exactly once at the survivors, with the
      injected duplicate COUNTED (never double-applied: bit-exactness
      above would fail);
    * ``replica_rps_scaling`` — the replica serves the identical bytes
      at >= 90% of its source aggregator's RPS under the same keep-alive
      client mix, so N replicas multiply read capacity.

    Trended under ``secondary.ha_*``: tick count, duplicate count,
    replica/primary RPS and their ratio.
    """
    import asyncio
    import time as _time

    import numpy as np

    from krr_tpu.core.runner import ScanSession
    from krr_tpu.core.config import Config
    from krr_tpu.federation.replica import ReplicaServer
    from krr_tpu.federation.shard import FederatedShard
    from krr_tpu.server.app import KrrServer
    from tests.fakes.federation import (
        FleetInventory,
        MultiClusterFleet,
        ORIGIN,
        history_factory,
    )

    ticks = max(3, int(os.environ.get("BENCH_HA_TICKS", 4)))
    workloads = max(1, int(os.environ.get("BENCH_HA_WORKLOADS", 2)))
    clients = max(2, int(os.environ.get("BENCH_HA_CLIENTS", 4)))
    requests_per_client = max(8, int(os.environ.get("BENCH_HA_REQUESTS", 40)))
    tick_seconds = 300.0
    start = ORIGIN + 3600.0
    fleet = MultiClusterFleet(
        clusters=2,
        namespaces_per_cluster=2,
        workloads_per_namespace=workloads,
        seed=59,
    )

    def config(**overrides) -> Config:
        defaults = dict(
            strategy="tdigest",
            quiet=True,
            server_port=0,
            scan_interval_seconds=tick_seconds,
            hysteresis_enabled=False,
            other_args={"history_duration": 1, "timeframe_duration": 1},
        )
        defaults.update(overrides)
        return Config(**defaults)

    def scans_by_key(state) -> dict:
        body = json.loads(state.peek().body_json.decode())
        return {
            "{cluster}/{namespace}/{name}/{container}/{kind}".format(**scan["object"]): scan
            for scan in body["scans"]
        }

    async def run() -> dict:
        now = [start]

        def aggregator() -> KrrServer:
            return KrrServer(
                config(federation_listen="127.0.0.1:0"),
                session=ScanSession(
                    config(),
                    inventory=FleetInventory(fleet, clusters=[]),
                    history_factory=history_factory(fleet),
                ),
                clock=lambda: now[0],
            )

        # Single-process control over the whole fleet.
        control = KrrServer(
            config(),
            session=ScanSession(
                config(),
                inventory=FleetInventory(fleet),
                history_factory=history_factory(fleet),
            ),
            clock=lambda: now[0],
        )
        for t in range(ticks):
            now[0] = start + t * tick_seconds
            assert await control.scheduler.run_once()

        now[0] = start
        primary, standby, single = aggregator(), aggregator(), aggregator()
        for server in (primary, standby, single):
            await server.start(run_scheduler=False)
        ring_spec = (
            f"a0=127.0.0.1:{primary.aggregator.port}|127.0.0.1:{standby.aggregator.port},"
            f"a1=127.0.0.1:{single.aggregator.port}"
        )
        shards = [
            FederatedShard(
                config(clusters=[c], federation_ring=ring_spec),
                session=ScanSession(
                    config(clusters=[c]),
                    inventory=FleetInventory(fleet, clusters=[c]),
                    history_factory=history_factory(fleet),
                ),
                clock=lambda: now[0],
                shard_id=c,
            )
            for c in fleet.clusters
        ]
        replica = ReplicaServer(
            config(
                federation_aggregator=f"127.0.0.1:{single.aggregator.port}",
                federation_shard_id="bench-replica",
            ),
            clock=lambda: now[0],
        )
        await replica.start()
        primary_dead = [False]

        async def wait(predicate, message, timeout=30.0):
            deadline = _time.monotonic() + timeout
            while not predicate():
                assert _time.monotonic() < deadline, f"ha: timed out waiting for {message}"
                await asyncio.sleep(0.01)

        def live_servers():
            return [standby, single] if primary_dead[0] else [primary, standby, single]

        async def ring_round(t: int) -> None:
            now[0] = start + t * tick_seconds
            for shard in shards:
                assert await shard.tick(now[0])
            by_port = {s.aggregator.port: s for s in live_servers()}

            def enqueued() -> bool:
                for shard in shards:
                    for uplink in shard._uplinks:
                        server = by_port.get(uplink.port)
                        if server is None:
                            continue  # the killed primary
                        status = server.aggregator._shards.get(uplink.stream_id)
                        if status is None or status.enqueued < shard.epoch:
                            return False
                return True

            await wait(enqueued, f"tick {t} records to enqueue everywhere")
            for server in live_servers():
                assert await server.scheduler.run_once()
            for shard in shards:
                for uplink in shard._uplinks:
                    if uplink.port in by_port:
                        await wait(
                            lambda u=uplink, s=shard: u.acked >= s.epoch,
                            f"tick {t} acks",
                        )

        try:
            await ring_round(0)

            # Duplicate injection: tick, wait for the standby to ENQUEUE the
            # epoch-2 records, then tear its connections before the aggregate
            # tick acks them. The reconnect's WELCOME reports the APPLIED
            # watermark (1), so the shard re-sends epoch 2 — which the standby
            # must count as a duplicate and never double-apply.
            now[0] = start + 1 * tick_seconds
            for shard in shards:
                assert await shard.tick(now[0])
            await wait(
                lambda: all(
                    server.aggregator._shards.get(f"{s.shard_id}/{node}") is not None
                    and server.aggregator._shards[f"{s.shard_id}/{node}"].enqueued >= s.epoch
                    for s in shards
                    for server, node in ((primary, "a0"), (standby, "a0"), (single, "a1"))
                ),
                "tick 2 records to enqueue before the tear",
            )
            for shard in shards:
                shard._node_uplinks["a0"][1]._disconnect()
                await shard._pump()
            await wait(
                lambda: sum(s.duplicates for s in standby.aggregator._shards.values())
                >= len(shards),
                "re-sent records to count as duplicates",
            )
            for server in (primary, standby, single):
                assert await server.scheduler.run_once()
            for shard in shards:
                assert await shard.wait_acked(shard.epoch, timeout=10.0)
            duplicates = int(
                sum(s.duplicates for s in standby.aggregator._shards.values())
            )

            # Kill the HA pair's primary; the soak continues on the standby.
            await primary.shutdown()
            primary_dead[0] = True
            for t in range(2, ticks):
                await ring_round(t)

            # Gate 1: union of the surviving ring stores + served scans is
            # bit-exact, per key, against the single-process control.
            control_store = control.state.store
            control_index = {k: i for i, k in enumerate(control_store.keys)}
            arrays = ("cpu_counts", "cpu_total", "cpu_peak", "mem_total", "mem_peak")
            merged_keys: list = []
            bitexact, detail = True, ""
            for server in (standby, single):
                store = server.state.store
                for i, key in enumerate(store.keys):
                    merged_keys.append(key)
                    j = control_index.get(key)
                    if j is None:
                        bitexact, detail = False, f"unexpected key {key}"
                        continue
                    for attr in arrays:
                        if not np.array_equal(
                            getattr(store, attr)[i], getattr(control_store, attr)[j]
                        ):
                            bitexact, detail = False, f"{attr} differs at {key}"
            if sorted(merged_keys) != sorted(control_store.keys):
                bitexact, detail = False, "merged ring keys != control keys"
            control_scans = scans_by_key(control.state)
            served: dict = {}
            for server in (standby, single):
                served.update(scans_by_key(server.state))
            if served != control_scans:
                bitexact, detail = False, "served response scans != control scans"

            # Gate 2: zero lost epochs, exactly-once apply at the survivors.
            survivor_ports = {standby.aggregator.port, single.aggregator.port}
            lost = [
                (uplink.stream_id, uplink.port, uplink.acked, shard.epoch)
                for shard in shards
                for uplink in shard._uplinks
                if uplink.port in survivor_ports and uplink.acked != shard.epoch
            ]
            applied_ok = all(
                s.applied == ticks
                for server in (standby, single)
                for s in server.aggregator._shards.values()
            )

            # Gate 3: replica converges on the source's published epoch and
            # serves byte-identical bodies at matching RPS.
            await wait(
                lambda: replica.state.publish_epoch == single.state.publish_epoch
                and replica.state.publish_epoch > 0,
                "replica to converge on the source epoch",
            )

            async def one_get(port: int):
                reader = KeepAliveReader(port)
                await reader.connect()
                try:
                    return await reader.get("/recommendations")
                finally:
                    await reader.close()

            src_status, src_headers, src_body, _ = await one_get(single.port)
            rep_status, rep_headers, rep_body, _ = await one_get(replica.port)
            replica_identical = (
                src_status == rep_status == 200
                and src_body == rep_body
                and src_headers.get("etag") == rep_headers.get("etag")
                and src_headers.get("x-krr-epoch") == rep_headers.get("x-krr-epoch")
            )

            async def measure_rps(port: int) -> float:
                readers = [KeepAliveReader(port) for _ in range(clients)]
                for r in readers:
                    await r.connect()
                latencies: list = []

                async def worker(r) -> None:
                    for _ in range(requests_per_client):
                        status, _headers, body, latency = await r.get("/recommendations")
                        assert status == 200 and body, f"ha reader got {status}"
                        latencies.append(latency)

                begun = _time.perf_counter()
                await asyncio.gather(*(worker(r) for r in readers))
                wall = _time.perf_counter() - begun
                for r in readers:
                    await r.close()
                return len(latencies) / max(wall, 1e-9)

            # Interleave best-of-two on each side to damp scheduler noise —
            # the gate compares the two, not an absolute throughput.
            primary_rps = max(await measure_rps(single.port), await measure_rps(single.port))
            replica_rps = max(await measure_rps(replica.port), await measure_rps(replica.port))

            return {
                "bitexact": bitexact,
                "detail": detail,
                "duplicates": duplicates,
                "lost": lost,
                "applied_ok": applied_ok,
                "replica_identical": replica_identical,
                "primary_rps": primary_rps,
                "replica_rps": replica_rps,
                "rows": len(control_store.keys),
            }
        finally:
            for shard in shards:
                await shard.close()
            await replica.shutdown()
            for server in (primary, standby, single):
                await server.shutdown()
            await control.shutdown()

    report = asyncio.run(run())
    ratio = report["replica_rps"] / max(report["primary_rps"], 1e-9)
    secondary["ha_ticks"] = float(ticks)
    secondary["ha_rows"] = float(report["rows"])
    secondary["ha_duplicates"] = float(report["duplicates"])
    secondary["ha_primary_rps"] = round(report["primary_rps"], 1)
    secondary["ha_replica_rps"] = round(report["replica_rps"], 1)
    secondary["ha_replica_rps_ratio"] = round(ratio, 3)
    secondary["ha_bitexact"] = 1.0 if report["bitexact"] else 0.0
    secondary["ha_failover_zero_lost_epochs"] = (
        1.0 if not report["lost"] and report["applied_ok"] else 0.0
    )
    print(
        f"bench: ha 2-node ring x {ticks} ticks -> primary killed, "
        f"{report['duplicates']} duplicate(s) absorbed, merged bit-exact: "
        f"{report['bitexact']}; replica {report['replica_rps']:.0f} rps vs "
        f"source {report['primary_rps']:.0f} rps (ratio {ratio:.2f})",
        file=sys.stderr,
    )
    check("ha_bitexact", report["bitexact"], report["detail"])
    check(
        "ha_failover_zero_lost_epochs",
        not report["lost"] and report["applied_ok"] and report["duplicates"] >= 2,
        f"lost={report['lost']}, applied_ok={report['applied_ok']}, "
        f"duplicates={report['duplicates']}",
    )
    check(
        "replica_rps_scaling",
        report["replica_identical"] and ratio >= 0.9,
        f"identical={report['replica_identical']}, replica={report['replica_rps']:.0f} "
        f"rps, source={report['primary_rps']:.0f} rps, ratio={ratio:.2f}",
    )


def fleet_obs_leg(secondary: dict, check) -> None:
    """Fleet-observability gates (`krr_tpu.obs.trace` stitching +
    `krr_tpu.federation` freshness lineage): two in-process scanner shards
    stream into an aggregator serve whose epoch feed drives a read replica
    — every process recording its own trace ring — then the identical soak
    repeats with ``--no-lineage`` as the overhead control. Three gates:

    * ``fleet_trace_stitched`` — ``stitch_chrome`` over the four processes'
      trace exports joins the shard ``scan``, aggregator ``apply_record``,
      and replica ``install`` spans into one causally-connected stitched
      component, with every remote parent reference resolving;
    * ``fleet_freshness_monotonic`` — every published epoch's lineage chain
      (newest sample → fold → apply → publish → install) is monotone
      non-decreasing, install receipts included, and all four
      ``krr_tpu_e2e_freshness_seconds{stage}`` histograms actually fired;
    * ``fleet_lineage_overhead`` — the lineage-stamped soak's tick wall is
      within 2% of the no-lineage control's (plus a 50 ms toy-scale noise
      floor), and both runs' merged stores are bit-identical per key
      (lineage is metadata-only by construction).

    Trended under ``secondary.fleet_*``: soak walls, the overhead delta,
    stitched component/lane counts, and lineage epoch depth.
    """
    import asyncio
    import time as _time

    from krr_tpu.core.runner import ScanSession
    from krr_tpu.core.config import Config
    from krr_tpu.federation.replica import ReplicaServer
    from krr_tpu.federation.shard import FederatedShard
    from krr_tpu.obs.trace import stitch_chrome
    from krr_tpu.server.app import KrrServer
    from tests.fakes.federation import (
        FleetInventory,
        MultiClusterFleet,
        ORIGIN,
        history_factory,
        stores_bitexact_by_key,
    )

    ticks = max(2, int(os.environ.get("BENCH_FLEETOBS_TICKS", 4)))
    workloads = max(1, int(os.environ.get("BENCH_FLEETOBS_WORKLOADS", 2)))
    tick_seconds = 300.0
    start = ORIGIN + 3600.0
    fleet = MultiClusterFleet(
        clusters=2,
        namespaces_per_cluster=2,
        workloads_per_namespace=workloads,
        seed=61,
    )

    def config(**overrides) -> Config:
        defaults = dict(
            strategy="tdigest",
            quiet=True,
            server_port=0,
            scan_interval_seconds=tick_seconds,
            hysteresis_enabled=False,
            other_args={"history_duration": 1, "timeframe_duration": 1},
        )
        defaults.update(overrides)
        return Config(**defaults)

    async def soak(lineage: bool) -> dict:
        now = [start]
        server = KrrServer(
            config(
                federation_listen="127.0.0.1:0",
                federation_lineage_enabled=lineage,
            ),
            session=ScanSession(
                config(),
                inventory=FleetInventory(fleet, clusters=[]),
                history_factory=history_factory(fleet),
            ),
            clock=lambda: now[0],
        )
        await server.start(run_scheduler=False)
        shards = [
            FederatedShard(
                config(
                    clusters=[c],
                    federation_aggregator=f"127.0.0.1:{server.aggregator.port}",
                    federation_lineage_enabled=lineage,
                ),
                session=ScanSession(
                    config(clusters=[c]),
                    inventory=FleetInventory(fleet, clusters=[c]),
                    history_factory=history_factory(fleet),
                ),
                clock=lambda: now[0],
                shard_id=c,
            )
            for c in fleet.clusters
        ]
        replica = ReplicaServer(
            config(
                federation_aggregator=f"127.0.0.1:{server.aggregator.port}",
                federation_shard_id="bench-replica",
                federation_backoff_cap_seconds=0.2,
            ),
            clock=lambda: now[0],
        )
        await replica.start()

        async def wait(predicate, message, timeout=30.0):
            deadline = _time.monotonic() + timeout
            while not predicate():
                assert (
                    _time.monotonic() < deadline
                ), f"fleet_obs: timed out waiting for {message}"
                await asyncio.sleep(0.01)

        wall = 0.0
        try:
            agg = server.aggregator
            await wait(lambda: replica.client.connected, "replica subscribe")
            for t in range(ticks):
                now[0] = start + t * tick_seconds
                for shard in shards:
                    begin = _time.perf_counter()
                    assert await shard.tick(now[0])
                    wall += _time.perf_counter() - begin
                await wait(
                    lambda: all(
                        s.shard_id in agg._shards
                        and agg._shards[s.shard_id].enqueued >= s.epoch
                        for s in shards
                    ),
                    f"tick {t} records to enqueue",
                )
                begin = _time.perf_counter()
                assert await server.scheduler.run_once()
                wall += _time.perf_counter() - begin
                for shard in shards:
                    assert await shard.wait_acked(shard.epoch, timeout=10.0)
                await wait(
                    lambda: replica.client.feed_epoch >= agg._feed_epoch,
                    f"tick {t} replica install",
                )
            if lineage:
                # The replica's install receipt travels back over the feed
                # socket — the lineage chain's last hop must land before the
                # rings are read.
                await wait(
                    lambda: agg.newest_installed_lineage() is not None,
                    "a replica install ack",
                )
            payloads = [s.tracer.export_chrome() for s in shards] + [
                server.session.tracer.export_chrome(),
                replica.tracer.export_chrome(),
            ]
            metrics = server.state.metrics
            return {
                "wall": wall,
                "store": server.state.store,
                "payloads": payloads,
                "lineage": agg.epoch_lineage(n=64),
                "installed": agg.newest_installed_lineage(),
                "stage_counts": {
                    stage: metrics.value(
                        "krr_tpu_e2e_freshness_seconds_count", stage=stage
                    )
                    for stage in ("fold", "apply", "publish", "install")
                },
            }
        finally:
            for shard in shards:
                await shard.close()
            await replica.shutdown()
            await server.shutdown()

    control = asyncio.run(soak(lineage=False))
    report = asyncio.run(soak(lineage=True))

    # Stitched-trace gate: one component must carry all three cross-process
    # hops, and every re-parented remote span must resolve inside the merge.
    stitched = stitch_chrome(report["payloads"])
    spans = [e for e in stitched["traceEvents"] if e.get("ph") == "X"]
    ids_by_pid: dict = {}
    names_by_pid: dict = {}
    for event in spans:
        ids_by_pid.setdefault(event["pid"], set()).add(event["args"].get("span_id"))
        names_by_pid.setdefault(event["pid"], set()).add(event["name"])
    joined = [
        pid
        for pid, names in names_by_pid.items()
        if {"scan", "apply_record", "install"} <= names
    ]
    remote_spans = [e for e in spans if e["args"].get("remote")]
    remote_resolved = all(
        e["args"].get("parent_id") in ids_by_pid.get(e["pid"], ())
        for e in remote_spans
    )
    remote_installs = [e for e in remote_spans if e["name"] == "install"]
    lanes = max(
        (len({e["tid"] for e in spans if e["pid"] == pid}) for pid in joined),
        default=0,
    )
    stitched_ok = bool(joined) and bool(remote_installs) and remote_resolved

    # Lineage-monotonicity gate over every retained epoch record.
    def monotone() -> "tuple[bool, str]":
        if not report["lineage"]:
            return False, "no lineage records"
        for record in report["lineage"]:
            chain = [
                float(record["newest_sample_ts"]),
                float(record["fold_ts"]),
                float(record["apply_ts"]),
                float(record["publish_ts"]),
            ]
            if chain != sorted(chain):
                return False, f"epoch {record['epoch']} chain out of order: {chain}"
            for replica_id, install_ts in (record.get("installs") or {}).items():
                if float(install_ts) < float(record["publish_ts"]):
                    return False, (
                        f"epoch {record['epoch']} installed at {replica_id} "
                        "before its publish"
                    )
        if report["installed"] is None:
            return False, "no epoch carries a replica install receipt"
        return True, f"{len(report['lineage'])} epochs monotone"

    monotonic_ok, monotonic_detail = monotone()
    stages_ok = all(
        (report["stage_counts"].get(stage) or 0.0) >= 1.0
        for stage in ("fold", "apply", "publish", "install")
    )

    # Overhead gate: lineage stamping is metadata-only — same bytes in the
    # merged store, and a tick wall within 2% (50 ms floor at toy scale).
    equal, detail = stores_bitexact_by_key(report["store"], control["store"])
    overhead = report["wall"] - control["wall"]
    budget = max(0.02 * control["wall"], 0.05)

    secondary["fleet_obs_ticks"] = float(ticks)
    secondary["fleet_trace_stitched"] = 1.0 if stitched_ok else 0.0
    secondary["fleet_stitched_components"] = float(len(joined))
    secondary["fleet_stitched_lanes"] = float(lanes)
    secondary["fleet_freshness_monotonic"] = (
        1.0 if monotonic_ok and stages_ok else 0.0
    )
    secondary["fleet_lineage_epochs"] = float(len(report["lineage"]))
    secondary["fleet_lineage_wall_seconds"] = round(report["wall"], 4)
    secondary["fleet_control_wall_seconds"] = round(control["wall"], 4)
    secondary["fleet_lineage_overhead_seconds"] = round(overhead, 4)
    secondary["fleet_lineage_bitexact"] = 1.0 if equal else 0.0
    print(
        f"bench: fleet obs 2 shards + replica x {ticks} ticks -> "
        f"{len(joined)} stitched component(s) ({lanes} lanes), "
        f"{len(report['lineage'])} lineage epochs, lineage wall "
        f"{report['wall']:.3f}s vs control {control['wall']:.3f}s "
        f"({overhead:+.3f}s)",
        file=sys.stderr,
    )
    check(
        "fleet_trace_stitched",
        stitched_ok,
        f"joined={len(joined)}, remote_installs={len(remote_installs)}, "
        f"remote_resolved={remote_resolved}",
    )
    check(
        "fleet_freshness_monotonic",
        monotonic_ok and stages_ok,
        f"{monotonic_detail}; stage counts={report['stage_counts']}",
    )
    check(
        "fleet_lineage_overhead",
        equal and overhead <= budget,
        f"bitexact={equal} ({detail}), overhead={overhead:.3f}s "
        f"over budget={budget:.3f}s",
    )


def readpath_leg(secondary: dict, check) -> None:
    """High-QPS read-path loadtest (`krr_tpu.server.state.ResponseCache` +
    the app's conditional-GET / pushdown / bounded-render machinery):
    concurrent keep-alive readers hammer a LIVE serve — mixed formats,
    filters, pagination, compressed variants, and conditional
    revalidations — WHILE scheduler ticks publish underneath, against an
    uncached (`--no-response-cache`) control serving the same fleet.
    Records p50/p99 latency, RPS, cache hit rate, and bytes served under
    ``secondary.readpath_*``. Six parity-style gates:

    * steady-state cache hit rate ≥ 99% (hysteresis-quiet publishes keep
      the epoch, so the warm cache survives live ticks);
    * conditional revalidations return 304 with ZERO render work (the miss
      counter must not move under an If-None-Match burst);
    * filtered + paginated responses bit-identical to the pre-cache
      render-then-slice path on the same snapshot;
    * gzip variants round-trip to the identity bytes;
    * the LRU stays inside its entry/byte bounds under a
      filter-cardinality attack;
    * cached RPS beats the uncached control (≥ 10× at fleet scale,
      ≥ 2× at toy scale where render cost barely exceeds HTTP overhead).
    """
    import asyncio
    import gzip as _gzip

    import numpy as np

    from krr_tpu.core.config import Config
    from krr_tpu.core.runner import ScanSession
    from krr_tpu.models.allocations import ResourceAllocations, ResourceType
    from krr_tpu.models.objects import K8sObjectData
    from krr_tpu.models.result import Result
    from krr_tpu.server.app import KrrServer

    workloads = int(os.environ.get("BENCH_READPATH_WORKLOADS", 400))
    clients = int(os.environ.get("BENCH_READPATH_CLIENTS", 8))
    requests_per_client = int(os.environ.get("BENCH_READPATH_REQUESTS", 120))
    control_requests = max(8, requests_per_client // 6)

    alloc = ResourceAllocations(
        requests={ResourceType.CPU: None, ResourceType.Memory: None},
        limits={ResourceType.CPU: None, ResourceType.Memory: None},
    )
    objects = [
        K8sObjectData(
            cluster="c", namespace=f"ns{i % 8}", name=f"w{i}", kind="Deployment",
            container="main", pods=[f"w{i}-0"], allocations=alloc,
        )
        for i in range(workloads)
    ]
    rng = np.random.default_rng(61)
    cpu_series = rng.gamma(2.0, 0.05, (workloads, 12))
    mem_series = rng.uniform(5e7, 4e8, (workloads, 12))
    by_name = {obj.name: i for i, obj in enumerate(objects)}

    class Inventory:
        async def list_clusters(self):
            return ["c"]

        async def list_scannable_objects(self, clusters):
            return list(objects)

    class Source:
        """Deterministic: the full backfill window carries the fleet's
        samples, delta windows are QUIET (no new samples — a no-op fold),
        so every live publish is byte-identical and the epoch holds — the
        hysteresis steady state the cache is designed for."""

        async def gather_fleet(self, objs, history_seconds, step_seconds, **kw):
            rows = [by_name[obj.name] for obj in objs]
            if history_seconds < 3000:  # a delta tick, not the backfill
                quiet = np.empty(0)
                return {
                    resource: [{obj.pods[0]: quiet} for obj in objs]
                    for resource in (ResourceType.CPU, ResourceType.Memory)
                }
            return {
                ResourceType.CPU: [{objs[j].pods[0]: cpu_series[i]} for j, i in enumerate(rows)],
                ResourceType.Memory: [{objs[j].pods[0]: mem_series[i]} for j, i in enumerate(rows)],
            }

    def build_server(now, **overrides) -> KrrServer:
        config = Config(
            strategy="tdigest", quiet=True, server_port=0,
            hysteresis_enabled=False,
            response_cache_max_entries=64,
            other_args={"history_duration": 1, "timeframe_duration": 1},
            **overrides,
        )
        session = ScanSession(
            config, inventory=Inventory(), history_factory=lambda cluster: Source()
        )
        return KrrServer(config, session=session, clock=lambda: now[0])

    Reader = KeepAliveReader

    GZIP = (("Accept-Encoding", "gzip"),)

    async def run() -> dict:
        now = [1_700_000_000.0]
        ks = build_server(now)
        await ks.start(run_scheduler=False)
        control = build_server([now[0]], response_cache_enabled=False)
        await control.start(run_scheduler=False)
        try:
            assert await ks.scheduler.run_once()
            assert await control.scheduler.run_once()
            metrics = ks.state.metrics
            prime = Reader(ks.port)
            await prime.connect()

            _status, h, identity_body, _ = await prime.get("/recommendations")
            etag = h["etag"]
            #: The cacheable mix (distinct cache keys), primed once so the
            #: timed phase measures STEADY STATE.
            mix = [
                ("/recommendations", GZIP),
                ("/recommendations?format=yaml", ()),
                ("/recommendations?namespace=ns1", ()),
                ("/recommendations?limit=20&offset=40", ()),
            ]
            for target, headers in mix:
                status, _h, _b, _lat = await prime.get(target, headers)
                assert status == 200, (target, status)

            # Gate: pushdown bit-identity vs the render-then-slice oracle.
            snapshot = ks.state.peek()

            def golden(fmt="json", namespaces=(), limit=None, offset=0) -> bytes:
                scans = [
                    s for s in snapshot.result.scans
                    if not namespaces or s.object.namespace in namespaces
                ]
                scans = scans[offset:(offset + limit) if limit else None]
                return Result(scans=scans).format(fmt).encode()

            _s, _h, filtered, _lat = await prime.get("/recommendations?namespace=ns1")
            _s, _h, paged, _lat = await prime.get("/recommendations?limit=20&offset=40")
            _s, _h, fyaml, _lat = await prime.get("/recommendations?format=yaml&namespace=ns2")
            pushdown_ok = (
                filtered == golden(namespaces={"ns1"})
                and paged == golden(limit=20, offset=40)
                and fyaml == golden("yaml", namespaces={"ns2"})
            )

            # Gate: gzip round-trips to the identity bytes.
            _s, gz_headers, gz_body, _lat = await prime.get("/recommendations", GZIP)
            gzip_ok = (
                gz_headers.get("content-encoding") == "gzip"
                and _gzip.decompress(gz_body) == identity_body
            )

            # Gate: 304 revalidations do ZERO render work.
            misses_before = metrics.total("krr_tpu_http_cache_misses_total")
            revalidations = 0
            for _ in range(32):
                status, _h, body, _lat = await prime.get(
                    "/recommendations", (("If-None-Match", etag),)
                )
                revalidations += int(status == 304 and body == b"")
            zero_render_304 = (
                revalidations == 32
                and metrics.total("krr_tpu_http_cache_misses_total") == misses_before
            )

            # Timed steady-state phase: concurrent keep-alive readers over
            # the full mix (bare identity + cached variants + conditionals)
            # WHILE scheduler ticks publish underneath.
            hits_before = metrics.total("krr_tpu_http_cache_hits_total")
            misses_before = metrics.total("krr_tpu_http_cache_misses_total")
            cycle = [
                ("/recommendations", ()),
                ("/recommendations", (("If-None-Match", etag),)),
                *mix,
            ]
            latencies: list[float] = []
            served_bytes = [0]

            async def reader_task(reader: Reader, n: int) -> None:
                for i in range(n):
                    target, headers = cycle[i % len(cycle)]
                    status, _h, body, latency = await reader.get(target, headers)
                    assert status in (200, 304), (target, status)
                    latencies.append(latency)
                    served_bytes[0] += len(body)

            readers = [Reader(ks.port) for _ in range(clients)]
            for reader in readers:
                await reader.connect()
            wall_start = time.perf_counter()
            tasks = [
                asyncio.create_task(reader_task(reader, requests_per_client))
                for reader in readers
            ]
            # Live publishes mid-load: byte-identical content keeps the
            # epoch (suppression discipline), so the cache must stay warm.
            for _ in range(2):
                await asyncio.sleep(0.02)
                now[0] += 120.0
                assert await ks.scheduler.run_once()
            await asyncio.gather(*tasks)
            wall = time.perf_counter() - wall_start
            for reader in readers:
                await reader.close()

            hits = metrics.total("krr_tpu_http_cache_hits_total") - hits_before
            misses = metrics.total("krr_tpu_http_cache_misses_total") - misses_before
            hit_pct = 100.0 * hits / max(1.0, hits + misses)

            # Apples-to-apples ratio phase: the SAME 4-target cacheable mix
            # the uncached control serves below, against the cached server —
            # the mixed phase above includes near-free bare/304 requests
            # that would inflate the cached side of the ratio.
            mix_latencies: list[float] = []

            async def mix_task(reader: Reader, n: int) -> None:
                for i in range(n):
                    target, headers = mix[i % len(mix)]
                    status, _h, _b, latency = await reader.get(target, headers)
                    assert status == 200, (target, status)
                    mix_latencies.append(latency)

            mix_readers = [Reader(ks.port) for _ in range(clients)]
            for reader in mix_readers:
                await reader.connect()
            mix_start = time.perf_counter()
            await asyncio.gather(
                *[asyncio.create_task(mix_task(r, control_requests)) for r in mix_readers]
            )
            mix_wall = time.perf_counter() - mix_start
            for reader in mix_readers:
                await reader.close()
            cacheable_rps = len(mix_latencies) / max(mix_wall, 1e-9)

            # LRU bound under a filter-cardinality attack.
            for i in range(3 * ks.config.response_cache_max_entries):
                await prime.get(f"/recommendations?namespace=attack{i}")
            cache = ks.state.response_cache
            lru_ok = (
                len(cache) <= ks.config.response_cache_max_entries
                and cache.nbytes <= int(ks.config.response_cache_max_mb * (1 << 20))
            )
            await prime.close()

            # Uncached control: the SAME cacheable mix, rendered per
            # request (--no-response-cache), smaller request count (it is
            # the slow side by design).
            control_latencies: list[float] = []

            async def control_task(reader: Reader, n: int) -> None:
                for i in range(n):
                    target, headers = mix[i % len(mix)]
                    status, _h, _b, latency = await reader.get(target, headers)
                    assert status == 200, (target, status)
                    control_latencies.append(latency)

            control_readers = [Reader(control.port) for _ in range(clients)]
            for reader in control_readers:
                await reader.connect()
            control_start = time.perf_counter()
            await asyncio.gather(
                *[asyncio.create_task(control_task(r, control_requests)) for r in control_readers]
            )
            control_wall = time.perf_counter() - control_start
            for reader in control_readers:
                await reader.close()

            # ``rps`` is the full production-like mix (bare + conditionals
            # included); the vs-uncached ratio instead uses the dedicated
            # cacheable-mix phase above, which mirrors the control exactly.
            total_requests = len(latencies)
            rps = total_requests / max(wall, 1e-9)
            control_rps = len(control_latencies) / max(control_wall, 1e-9)
            ordered = sorted(latencies)
            timeline_records = ks.state.timeline.records()
            readpath_recorded = any(
                (r.get("readpath") or {}).get("requests", 0) > 0 for r in timeline_records
            )
            return {
                "requests": total_requests,
                "wall": wall,
                "rps": rps,
                "cacheable_rps": cacheable_rps,
                "p50_ms": ordered[len(ordered) // 2] * 1e3,
                "p99_ms": ordered[min(len(ordered) - 1, int(len(ordered) * 0.99))] * 1e3,
                "hit_pct": hit_pct,
                "bytes": served_bytes[0],
                "revalidations": revalidations,
                "control_rps": control_rps,
                "pushdown_ok": pushdown_ok,
                "gzip_ok": gzip_ok,
                "zero_render_304": zero_render_304,
                "lru_ok": lru_ok,
                "readpath_recorded": readpath_recorded,
                "epoch": ks.state.peek().epoch,
            }
        finally:
            await ks.shutdown()
            await control.shutdown()

    report = asyncio.run(run())
    vs_uncached = report["cacheable_rps"] / max(report["control_rps"], 1e-9)
    secondary["readpath_workloads"] = float(workloads)
    secondary["readpath_clients"] = float(clients)
    secondary["readpath_requests"] = float(report["requests"])
    secondary["readpath_rps"] = round(report["rps"], 1)
    secondary["readpath_cacheable_rps"] = round(report["cacheable_rps"], 1)
    secondary["readpath_p50_ms"] = round(report["p50_ms"], 3)
    secondary["readpath_p99_ms"] = round(report["p99_ms"], 3)
    secondary["readpath_cache_hit_pct"] = round(report["hit_pct"], 2)
    secondary["readpath_bytes_mb"] = round(report["bytes"] / 1e6, 3)
    secondary["readpath_uncached_rps"] = round(report["control_rps"], 1)
    secondary["readpath_rps_vs_uncached"] = round(vs_uncached, 1)
    print(
        f"bench: readpath {workloads} workloads x {clients} keep-alive readers: "
        f"{report['requests']} requests in {report['wall']:.2f}s "
        f"({report['rps']:.0f} rps mixed, p50 {report['p50_ms']:.2f} ms, "
        f"p99 {report['p99_ms']:.2f} ms, hit rate {report['hit_pct']:.1f}%, "
        f"epoch held at {report['epoch']}); cacheable mix "
        f"{report['cacheable_rps']:.0f} rps vs uncached {report['control_rps']:.0f} rps "
        f"-> x{vs_uncached:.1f}",
        file=sys.stderr,
    )
    check(
        "readpath_hit_rate>=99%",
        report["hit_pct"] >= 99.0,
        f"steady-state cache hit rate {report['hit_pct']:.1f}%",
    )
    check(
        "readpath_304_zero_render",
        report["zero_render_304"],
        f"{report['revalidations']}/32 revalidations returned 304 without render work",
    )
    check("readpath_pushdown_bitexact", report["pushdown_ok"],
          "filtered/paginated responses diverged from render-then-slice")
    check("readpath_gzip_roundtrip", report["gzip_ok"],
          "gzip variant did not round-trip to the identity bytes")
    check("readpath_lru_bounded", report["lru_ok"],
          "response cache exceeded its entry/byte bounds under filter cardinality")
    check("readpath_timeline_recorded", report["readpath_recorded"],
          "no timeline record carried read-path tick stats")
    # The RPS ratio bar scales with fleet width: at toy (smoke) scale the
    # render cost barely exceeds raw HTTP overhead, so 10x is a fleet-scale
    # acceptance bar, not a smoke one.
    bar = 10.0 if workloads >= 200 else 2.0
    check(
        f"readpath_rps>={bar:.0f}x_uncached",
        vs_uncached >= bar,
        f"cached {report['cacheable_rps']:.0f} rps vs uncached "
        f"{report['control_rps']:.0f} rps (x{vs_uncached:.1f} < x{bar:.0f})",
    )


def obs_leg(secondary: dict, check) -> None:
    """Tracing-overhead leg: the SAME in-process digest scan (fake inventory
    + deterministic history source, streamed pipeline, tdigest
    digest-ingest) run with the no-op tracer and with a recording tracer +
    metrics registry. Two gates ride on it: the traced wall must stay
    within 2% of the plain wall (with a 10 ms absolute floor — at smoke
    scale 2% of a ~50 ms scan is below timer noise, while 10 ms of genuine
    span overhead would mean a real hot-path regression), and the
    recommendations must be BIT-exact — observability must never perturb
    results. Reported under ``secondary.obs_*``."""
    import asyncio
    import contextlib
    import io

    import numpy as np

    from krr_tpu.core.config import Config
    from krr_tpu.core.runner import Runner
    from krr_tpu.models.allocations import ResourceAllocations, ResourceType
    from krr_tpu.models.objects import K8sObjectData
    from krr_tpu.obs.metrics import MetricsRegistry
    from krr_tpu.obs.trace import NULL_TRACER, Tracer

    rows = int(os.environ.get("BENCH_OBS_ROWS", 256))
    samples = int(os.environ.get("BENCH_OBS_SAMPLES", 4096))
    runs = max(2, int(os.environ.get("BENCH_OBS_RUNS", 5)))

    rng = np.random.default_rng(23)
    alloc = ResourceAllocations(
        requests={ResourceType.CPU: None, ResourceType.Memory: None},
        limits={ResourceType.CPU: None, ResourceType.Memory: None},
    )
    objects = [
        K8sObjectData(
            cluster=None, namespace=f"ns{i % 8}", name=f"w{i}", kind="Deployment",
            container="main", pods=[f"w{i}-0"], allocations=alloc,
        )
        for i in range(rows)
    ]
    # Series precomputed ONCE and shared by every run: both tracer modes
    # scan identical data, and the timed region holds no rng work.
    series = {
        ResourceType.CPU: [{f"w{i}-0": rng.gamma(2.0, 0.05, samples)} for i in range(rows)],
        ResourceType.Memory: [{f"w{i}-0": rng.uniform(5e7, 4e8, samples)} for i in range(rows)],
    }
    by_key = {(obj.namespace, obj.name): i for i, obj in enumerate(objects)}

    class Inventory:
        async def list_clusters(self):
            return None

        async def list_scannable_objects(self, clusters):
            return objects

    class Source:
        async def gather_fleet(self, objs, history_seconds, step_seconds, **kw):
            indices = [by_key[(obj.namespace, obj.name)] for obj in objs]
            return {r: [series[r][i] for i in indices] for r in ResourceType}

    def scan(tracer):
        config = Config(quiet=True, format="json", strategy="tdigest",
                        other_args={"digest_ingest": True})
        r = Runner(
            config, inventory=Inventory(), history_factory=lambda cluster: Source(),
            tracer=tracer, metrics=MetricsRegistry(),
        )
        with contextlib.redirect_stdout(io.StringIO()):
            return asyncio.run(r.run())

    scan(NULL_TRACER)  # warmup: jit compile + import costs out of the timing
    tracer = None
    plain_times, traced_times = [], []
    plain_result = traced_result = None
    for _ in range(runs):  # interleaved so machine-load drift hits both modes
        start = time.perf_counter()
        plain_result = scan(NULL_TRACER)
        plain_times.append(time.perf_counter() - start)
        tracer = Tracer(ring_scans=4)
        start = time.perf_counter()
        traced_result = scan(tracer)
        traced_times.append(time.perf_counter() - start)

    plain_best, traced_best = min(plain_times), min(traced_times)
    overhead = traced_best - plain_best
    overhead_pct = 100.0 * overhead / plain_best
    span_count = len(tracer.traces()[-1])
    secondary["obs_plain_scan_seconds"] = round(plain_best, 4)
    secondary["obs_traced_scan_seconds"] = round(traced_best, 4)
    secondary["obs_trace_overhead_pct"] = round(max(0.0, overhead_pct), 2)
    secondary["obs_spans_per_scan"] = span_count
    analyze_smoke_leg(tracer, secondary, check)
    print(
        f"bench: obs overhead plain {plain_best:.4f}s vs traced {traced_best:.4f}s "
        f"({max(0.0, overhead_pct):.2f}% over {runs} interleaved runs, "
        f"{span_count} spans/scan)",
        file=sys.stderr,
    )
    check(
        "obs_overhead<2%",
        overhead <= max(0.02 * plain_best, 0.010),
        f"traced {traced_best:.4f}s vs plain {plain_best:.4f}s (+{overhead_pct:.2f}%)",
    )
    check(
        "obs_bitexact",
        plain_result.model_dump_json() == traced_result.model_dump_json(),
        "tracing changed the recommendations",
    )


def analyze_smoke_leg(tracer, secondary: dict, check) -> None:
    """`krr-tpu analyze` smoke: dump the obs leg's recorded ring as a
    Chrome trace file, run the real CLI subprocess over it, and assert the
    attribution report comes back (rc 0, ≥1 scan, categories partition the
    wall). A break anywhere in trace export → chrome re-import → sweep →
    CLI wiring fails the round like a parity break. Reported under
    ``secondary.analyze_*``."""
    import subprocess
    import tempfile

    from krr_tpu.obs.trace import write_chrome_trace

    here = os.path.dirname(os.path.abspath(__file__))
    with tempfile.TemporaryDirectory() as tmp:
        trace_path = os.path.join(tmp, "scan-trace.json")
        write_chrome_trace(tracer, trace_path)
        proc = subprocess.run(
            [sys.executable, "-m", "krr_tpu", "analyze", "--trace", trace_path, "--format", "json"],
            capture_output=True,
            text=True,
            timeout=300,
            cwd=here,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
    report: dict = {}
    if proc.returncode == 0:
        try:
            report = json.loads(proc.stdout)
        except ValueError:
            pass
    scans = report.get("scans", [])
    partitioned = all(
        abs(sum(s["categories"].values()) - s["wall_seconds"])
        <= max(0.01 * s["wall_seconds"], 1e-3)
        for s in scans
    )
    ok = proc.returncode == 0 and bool(scans) and partitioned
    secondary["analyze_smoke"] = "ok" if ok else f"failed rc={proc.returncode}"
    secondary["analyze_scans"] = len(scans)
    print(
        f"bench: analyze smoke -> rc {proc.returncode}, {len(scans)} scan(s) attributed",
        file=sys.stderr,
    )
    check(
        "analyze_smoke",
        ok,
        f"rc={proc.returncode}, scans={len(scans)}, partitioned={partitioned}: "
        f"{proc.stderr[-300:]}",
    )


def sentinel_leg(secondary: dict, check) -> None:
    """Regression-sentinel gates (`krr_tpu.obs.sentinel` over
    `krr_tpu.obs.timeline`): two synthetic 60-tick timelines sharing
    byte-identical noise — a clean control and a twin with one injected
    fetch-transport regression (ttfb bulge) and one injected compute
    regression — driven through the SAME trend_report/sentinel code that
    serves ``GET /debug/timeline`` and ``krr-tpu analyze --trend``. Four
    parity-style gates:

    * detection — both injected regressions produce regressed verdicts;
    * attribution — the verdicts name fetch_transport (ttfb-dominated) and
      compute at the injected ticks;
    * zero false positives — the clean control produces NO verdicts, and
      the injected run flags only the injected ticks;
    * recorder overhead — the full per-tick recorder cost (record build +
      durable CRC-framed fsync'd append + sentinel classification) stays
      under 2% of the obs leg's measured scan wall (10 ms absolute floor,
      like the tracing-overhead gate).
    """
    import copy
    import tempfile

    import numpy as np

    from krr_tpu.obs.sentinel import RegressionSentinel, trend_report
    from krr_tpu.obs.timeline import ScanTimeline

    ticks = max(20, int(os.environ.get("BENCH_SENTINEL_TICKS", 60)))
    rng = np.random.default_rng(47)
    base = {
        "fetch_transport": 0.9,
        "fetch_decode": 0.25,
        "fetch_backoff": 0.0,
        "fetch_other": 0.1,
        "fold": 0.2,
        "compute": 0.35,
        "discover": 0.05,
        "publish": 0.05,
        "other": 0.0,
        "idle": 0.1,
    }

    def record(i: int) -> dict:
        cats = {k: round(v * float(1.0 + rng.normal(0, 0.04)), 6) for k, v in base.items()}
        phases = {
            "ttfb": round(0.5 * float(1.0 + rng.normal(0, 0.05)), 6),
            "body_read": round(0.3 * float(1.0 + rng.normal(0, 0.05)), 6),
            "connect": round(0.05 * float(1.0 + rng.normal(0, 0.05)), 6),
        }
        return {
            "v": 1,
            "ts": 1e9 + i * 300.0,
            "scan_id": f"bench-{i}",
            "kind": "delta",
            "wall": round(sum(cats.values()), 6),
            "categories": cats,
            "phases": phases,
            "rows": 256,
            "failed_rows": 0,
            "wire_bytes": 1 << 22,
            "queries": 16,
            "retries": 0,
            "publish": {"changed": 3, "suppressed": 1},
            "persist": {"seconds": 0.02, "bytes": 4096, "epoch": i + 1, "failing": False},
            "plan": {"coalesced": 2, "sharded": 1},
        }

    clean = [record(i) for i in range(ticks)]
    injected = copy.deepcopy(clean)
    fetch_at, compute_at = int(ticks * 0.6), int(ticks * 0.85)
    for i in (fetch_at, fetch_at + 1):
        injected[i]["categories"]["fetch_transport"] = round(
            injected[i]["categories"]["fetch_transport"] + 3.0, 6
        )
        injected[i]["phases"]["ttfb"] = round(injected[i]["phases"]["ttfb"] + 2.8, 6)
        injected[i]["wall"] = round(injected[i]["wall"] + 3.0, 6)
    for i in (compute_at, compute_at + 1):
        injected[i]["categories"]["compute"] = round(
            injected[i]["categories"]["compute"] + 2.0, 6
        )
        injected[i]["wall"] = round(injected[i]["wall"] + 2.0, 6)
    injected_ts = {injected[i]["ts"] for i in
                   (fetch_at, fetch_at + 1, compute_at, compute_at + 1)}

    control = trend_report(clean, warmup_scans=8)
    report = trend_report(injected, warmup_scans=8)
    fetch_verdicts = [v for v in report["regressions"] if v["dominant"] == "fetch_transport"]
    compute_verdicts = [v for v in report["regressions"] if v["dominant"] == "compute"]
    detected = bool(fetch_verdicts) and bool(compute_verdicts)
    attributed = (
        any(v["ts"] == injected[fetch_at]["ts"] and "ttfb-dominated" in v["suspect"]
            for v in fetch_verdicts)
        and any(v["ts"] == injected[compute_at]["ts"] for v in compute_verdicts)
    )
    spurious = [v for v in report["regressions"] if v["ts"] not in injected_ts]
    no_false_positives = control["regressed"] == 0 and not spurious

    # Recorder overhead: the whole per-tick cost — durable append (CRC frame
    # + fsync) plus sentinel classification — against a real scan wall.
    sentinel = RegressionSentinel(warmup_scans=8)
    with tempfile.TemporaryDirectory() as tmp:
        timeline = ScanTimeline.open(os.path.join(tmp, "timeline.log"))
        start = time.perf_counter()
        for r in injected:
            timeline.append(r)
            sentinel.observe(r, fire=False)
        recorder_seconds = time.perf_counter() - start
        timeline.close()
    per_tick = recorder_seconds / ticks
    scan_wall = float(secondary.get("obs_plain_scan_seconds") or 0.0)
    overhead_pct = 100.0 * per_tick / scan_wall if scan_wall > 0 else 0.0

    secondary["sentinel_ticks"] = float(ticks)
    secondary["sentinel_clean_regressions"] = float(control["regressed"])
    secondary["sentinel_injected_regressions"] = float(report["regressed"])
    secondary["sentinel_recorder_seconds_per_tick"] = round(per_tick, 6)
    secondary["timeline_overhead_pct"] = round(overhead_pct, 3)
    print(
        f"bench: sentinel {ticks}-tick timeline: injected run flagged "
        f"{report['regressed']} (fetch_transport {len(fetch_verdicts)}, compute "
        f"{len(compute_verdicts)}), clean control {control['regressed']}; recorder "
        f"{per_tick * 1e3:.2f} ms/tick ({overhead_pct:.2f}% of a "
        f"{scan_wall:.3f}s scan)",
        file=sys.stderr,
    )
    check(
        "sentinel_detects_injected",
        detected,
        f"fetch verdicts {len(fetch_verdicts)}, compute verdicts {len(compute_verdicts)}",
    )
    check(
        "sentinel_attribution_correct",
        attributed,
        f"regressions: {[(v['ts'], v['dominant'], v['suspect']) for v in report['regressions']]}",
    )
    check(
        "sentinel_zero_false_positives",
        no_false_positives,
        f"clean {control['regressed']}, spurious {[(v['ts'], v['dominant']) for v in spurious]}",
    )
    check(
        "timeline_overhead<2%",
        per_tick <= max(0.02 * scan_wall, 0.010),
        f"recorder {per_tick * 1e3:.2f} ms/tick vs scan wall {scan_wall:.4f}s "
        f"({overhead_pct:.2f}%)",
    )


def obs_device_leg(secondary: dict, check) -> None:
    """Device-observability leg (`krr_tpu.obs.device`): the SAME compute —
    one `SimpleStrategy.run_batch` over a fixed synthetic fleet — run with
    the inert NULL_DEVICE_OBS and with a recording DeviceObs (staged
    pack/quantile/round sub-spans, `block_until_ready` fencing, compile
    attribution, padding gauges). Gates mirror the scan-level obs leg:
    instrumented compute must stay within 2% wall of plain (10 ms absolute
    floor at smoke scale) and BIT-exact. Also asserts the device stages
    actually recorded: stage spans present, padding waste fired. Reported
    under ``secondary.obs_device_*``."""
    import numpy as np

    from krr_tpu.models.allocations import ResourceAllocations, ResourceType
    from krr_tpu.models.objects import K8sObjectData
    from krr_tpu.models.series import FleetBatch
    from krr_tpu.obs.device import NULL_DEVICE_OBS, DeviceObs
    from krr_tpu.obs.metrics import MetricsRegistry
    from krr_tpu.obs.trace import Tracer
    from krr_tpu.strategies.simple import SimpleStrategy, SimpleStrategySettings

    rows = int(os.environ.get("BENCH_OBS_ROWS", 256))
    samples = int(os.environ.get("BENCH_OBS_SAMPLES", 4096))
    runs = max(2, int(os.environ.get("BENCH_OBS_RUNS", 5)))

    rng = np.random.default_rng(29)
    alloc = ResourceAllocations(
        requests={ResourceType.CPU: None, ResourceType.Memory: None},
        limits={ResourceType.CPU: None, ResourceType.Memory: None},
    )
    objects = [
        K8sObjectData(
            cluster=None, namespace=f"ns{i % 8}", name=f"w{i}", kind="Deployment",
            container="main", pods=[f"w{i}-0"], allocations=alloc,
        )
        for i in range(rows)
    ]
    # Ragged on purpose (varying sample counts) so the padding gauges
    # measure genuine waste, not a degenerate all-full matrix.
    histories = {
        ResourceType.CPU: [
            {f"w{i}-0": rng.gamma(2.0, 0.05, samples - (i % 7) * (samples // 8))}
            for i in range(rows)
        ],
        ResourceType.Memory: [
            {f"w{i}-0": rng.uniform(5e7, 4e8, samples - (i % 5) * (samples // 8))}
            for i in range(rows)
        ],
    }
    batch = FleetBatch.build(objects, histories)
    strategy = SimpleStrategy(SimpleStrategySettings(use_pallas=False, use_mesh=False))
    strategy.run_batch(batch)  # warmup: jit compile out of the timing

    tracer = registry = None
    plain_times, traced_times = [], []
    plain_result = traced_result = None
    for _ in range(runs):  # interleaved so machine-load drift hits both modes
        strategy.obs = NULL_DEVICE_OBS
        start = time.perf_counter()
        plain_result = strategy.run_batch(batch)
        plain_times.append(time.perf_counter() - start)
        tracer, registry = Tracer(ring_scans=4), MetricsRegistry()
        strategy.obs = DeviceObs(tracer, registry)
        start = time.perf_counter()
        with tracer.span("compute", rows=rows):
            traced_result = strategy.run_batch(batch)
        traced_times.append(time.perf_counter() - start)
    strategy.obs = NULL_DEVICE_OBS

    plain_best, traced_best = min(plain_times), min(traced_times)
    overhead = traced_best - plain_best
    overhead_pct = 100.0 * overhead / plain_best
    stages = [s.name for s in tracer.traces()[-1] if s.name != "compute"]
    secondary["obs_device_plain_seconds"] = round(plain_best, 4)
    secondary["obs_device_traced_seconds"] = round(traced_best, 4)
    secondary["obs_device_overhead_pct"] = round(max(0.0, overhead_pct), 2)
    secondary["obs_device_stage_spans"] = len(stages)
    print(
        f"bench: obs-device overhead plain {plain_best:.4f}s vs traced {traced_best:.4f}s "
        f"({max(0.0, overhead_pct):.2f}% over {runs} interleaved runs, "
        f"stages {sorted(set(stages))})",
        file=sys.stderr,
    )
    check(
        "obs_device_overhead<2%",
        overhead <= max(0.02 * plain_best, 0.010),
        f"traced {traced_best:.4f}s vs plain {plain_best:.4f}s (+{overhead_pct:.2f}%)",
    )
    check(
        "obs_device_bitexact",
        repr(plain_result) == repr(traced_result),
        "device instrumentation changed the recommendations",
    )
    check(
        "obs_device_stages",
        {"pack", "quantile", "round"} <= set(stages),
        f"missing compute sub-spans: {sorted(set(stages))}",
    )
    waste = registry.value("krr_tpu_pad_waste_pct", resource="cpu")
    check(
        "obs_device_pad_waste",
        waste is not None and 0.0 < waste < 100.0,
        f"pad waste gauge: {waste}",
    )


def main() -> None:
    if "--smoke" in sys.argv:
        for key, value in SMOKE_DEFAULTS.items():
            os.environ.setdefault(key, value)
    # Shapes are aligned down to the kernel tile boundaries (8 rows, 128
    # lanes) so `fleet_exact` takes its zero-copy path: at ~10 GB of resident
    # history there is no HBM headroom for `_pad_inputs` to make padded
    # copies of both arrays. The defaults are already aligned.
    n_req = int(os.environ.get("BENCH_CONTAINERS", 10_000))
    t_req = int(os.environ.get("BENCH_TIMESTEPS", 120_960))
    n = max(8, n_req // 8 * 8)
    t = max(128, t_req // 128 * 128)
    if (n, t) != (n_req, t_req):
        print(
            f"bench: shape adjusted to tile boundaries: requested {n_req}x{t_req}, running {n}x{t}",
            file=sys.stderr,
        )
    chunk = int(os.environ.get("BENCH_CHUNK", 8_192))
    # 5 runs (round-2 verdict: 3 left round-over-round comparisons inside the
    # recorded 4.8% chip-load spread — best-of-5 tightens the floor).
    runs = max(1, int(os.environ.get("BENCH_RUNS", 5)))
    py_sample = int(os.environ.get("BENCH_PY_SAMPLE", 3))
    parity_rows = min(n, max(8, int(os.environ.get("BENCH_PARITY_ROWS", 512)) // 8 * 8))

    import jax
    import jax.numpy as jnp
    import numpy as np

    from krr_tpu.ops import digest as digest_ops
    from krr_tpu.ops.digest import DigestSpec
    from krr_tpu.ops.pallas_select import _fleet_exact_jnp, fleet_exact

    device = jax.devices()[0]
    print(f"bench: {n} containers x {t} timesteps on {device.platform}:{device.device_kind}", file=sys.stderr)

    # On-device data generation, chunked so RNG temp buffers stay small (a
    # one-shot gamma at [10k x 120k] OOMs on threefry temps alone). Arrays are
    # born at exactly [n, t], already tile-aligned (see main), so the fused
    # kernel never pads; any trailing partial chunk is generated as one extra
    # block.
    chunk = min(chunk, t)
    num_chunks = t // chunk
    remainder = t % chunk

    @jax.jit
    def generate(key):
        def cpu_like(block):
            return block * block * 0.8 + 1e-4  # right-skewed cpu-like values

        def body(i, buf):
            sub = jax.random.fold_in(key, i)
            block = cpu_like(jax.random.uniform(sub, (n, chunk), dtype=jnp.float32))
            return jax.lax.dynamic_update_slice(buf, block, (0, i * chunk))

        buf = jax.lax.fori_loop(0, num_chunks, body, jnp.zeros((n, t), jnp.float32))
        if remainder:
            tail = cpu_like(
                jax.random.uniform(jax.random.fold_in(key, num_chunks), (n, remainder), jnp.float32)
            )
            buf = jax.lax.dynamic_update_slice(buf, tail, (0, num_chunks * chunk))
        return buf

    values = generate(jax.random.PRNGKey(0))  # CPU histories
    mem_values = generate(jax.random.PRNGKey(1))  # memory histories (same shape)
    counts = jnp.full((n,), t, dtype=jnp.int32)
    _ = np.asarray(values[:1, :4])  # force generation
    _ = np.asarray(mem_values[:1, :4])

    parity_failures: list[str] = []

    def check(name: str, ok: bool, detail: str = "") -> None:
        if ok:
            print(f"bench: parity [{name}] ok", file=sys.stderr)
        else:
            parity_failures.append(name)
            print(f"bench: parity [{name}] FAILED {detail}", file=sys.stderr)

    def exact_step(values, counts):
        # The full exact strategy program — CPU p99 selection + memory peak —
        # in ONE dispatch with ONE readback (Pallas kernels on TPU, jnp
        # elsewhere; bit-identical). Round trips dominate at this speed.
        return fleet_exact(values, counts, mem_values, counts, 99.0)

    def timed(step) -> tuple[float, float]:
        """(best, spread_pct) over `runs` timed calls after a warmup."""
        _ = np.asarray(step(values, counts))  # warmup/compile
        times = []
        for _i in range(runs):
            start = time.perf_counter()
            _ = np.asarray(step(values, counts))
            times.append(time.perf_counter() - start)
        best = min(times)
        spread_pct = 100.0 * (max(times) - best) / best
        return best, spread_pct

    exact_elapsed, exact_spread = timed(exact_step)
    throughput = n / exact_elapsed
    print(
        f"bench: exact bisect+max {exact_elapsed:.3f}s (spread {exact_spread:.0f}% over {runs}) "
        f"-> {throughput:.0f} containers/s",
        file=sys.stderr,
    )

    # Measured dispatch floor: one trivial jit call + host readback. On the
    # tunneled chip this RTT is ~90 ms — ~2/3 of the headline measurement —
    # so the reported containers/s is a LOWER bound set by per-call latency,
    # not by the kernel: at 4x the rows over the same bytes the same program
    # measures ~2.4x the throughput (ARCHITECTURE.md records the sweep).
    tiny = jnp.ones((8, 128), jnp.float32)
    tiny_step = jax.jit(lambda a: a.sum(axis=1))
    _ = np.asarray(tiny_step(tiny))
    floor = min(
        _time_once(lambda: np.asarray(tiny_step(tiny))) for _ in range(5)
    )
    print(f"bench: dispatch+readback floor {floor * 1e3:.1f} ms", file=sys.stderr)

    # --- Amortized (pipelined) headline: the single-dispatch number above is
    # ~2/3 tunnel RTT at this speed, so it tracks rig latency, not kernel
    # work (round-3 verdict). Dispatch R independent copies of the SAME
    # program and sync ONCE on the last result: dispatches are async, the
    # device executes them back-to-back, and the RTT is paid once per R
    # programs instead of once per measurement. Throughput over n*R rows of
    # work then converges to the kernel's own rate (measured: 63k c/s raw →
    # 218k c/s at depth 16 on the tunneled v5e; per-call time approaches the
    # floor-corrected estimate, which cross-checks the subtraction). Also
    # report the floor-SUBTRACTED single-dispatch rate; the pipelined number
    # is the more stable of the two (no difference of noisy ~100 ms
    # quantities).
    pipeline_depth = max(2, int(os.environ.get("BENCH_PIPELINE_DEPTH", 16)))

    def dispatch_pipeline() -> None:
        results = [exact_step(values, counts) for _ in range(pipeline_depth)]
        _ = np.asarray(results[-1])  # one sync: all earlier programs precede it

    pipe_times = [_time_once(dispatch_pipeline) for _ in range(runs)]
    pipe_best = min(pipe_times)
    pipe_spread = 100.0 * (max(pipe_times) - pipe_best) / pipe_best
    pipelined_throughput = n * pipeline_depth / pipe_best
    # The subtraction is only meaningful when the floor is clearly below the
    # measurement (on a fast local backend, or under rig-RTT wobble, it can
    # meet or exceed it — a clamped divide would report ~1e13 containers/s
    # as a "cross-check"); report null instead and lean on the pipelined
    # number, which needs no subtraction.
    corrected_seconds = exact_elapsed - floor
    floor_corrected = n / corrected_seconds if corrected_seconds > 1e-3 else None
    vs_corrected = (
        f" vs floor-corrected {corrected_seconds * 1e3:.1f} ms"
        if floor_corrected is not None
        else " (floor within 1 ms of the measurement: floor-corrected rate not meaningful)"
    )
    print(
        f"bench: pipelined x{pipeline_depth} {pipe_best:.3f}s (spread {pipe_spread:.0f}%) "
        f"-> {pipelined_throughput:.0f} containers/s amortized "
        f"({pipe_best / pipeline_depth * 1e3:.1f} ms/call{vs_corrected})",
        file=sys.stderr,
    )

    # --- On-hardware parity gate, part 1: fused Pallas vs pure-jnp XLA.
    # Same chip, same subsample, two independent lowerings; the contract is
    # bit-identity (BASELINE.md correctness gate is ±1% vs the reference —
    # this is far stricter).
    sub_v = values[:parity_rows]
    sub_m = mem_values[:parity_rows]
    sub_c = counts[:parity_rows]
    got = np.asarray(fleet_exact(sub_v, sub_c, sub_m, sub_c, 99.0))
    want = np.asarray(_fleet_exact_jnp(sub_v, sub_c, sub_m, sub_c, jnp.float32(99.0), 31))
    check(
        "fleet_exact==jnp",
        bool(np.array_equal(got, want)),
        f"max |Δ| = {np.max(np.abs(got - want)) if got.shape == want.shape else 'shape'}",
    )
    exact_p99_sub = got[0]

    # Free the memory-history array before the sketch paths: both resident
    # plus sketch-build temporaries exceed a single chip's HBM.
    del exact_step
    mem_values = None

    secondary: dict = {}
    if not os.environ.get("BENCH_SKIP_DIGEST"):
        from krr_tpu.ops import topk_sketch as topk_ops
        from krr_tpu.ops.quantile import masked_max

        k = topk_ops.required_k(t, 99.0)

        @jax.jit
        def topk_step(values, counts):
            sketch = topk_ops.build_from_packed(values, counts, k=k, chunk_size=chunk)
            # The row max is the sketch's top-1 — no second matrix pass.
            return topk_ops.percentile(sketch, 99.0), topk_ops.peak(sketch)

        topk_elapsed, topk_spread = timed(topk_step)
        secondary["topk_containers_per_sec"] = round(n / topk_elapsed, 1)
        print(
            f"bench: exact topk sketch (K={k}, Pallas bisect+compact) {topk_elapsed:.3f}s "
            f"(spread {topk_spread:.0f}%) -> {n / topk_elapsed:.0f} containers/s "
            f"(streaming/mergeable path, zero error — tdigest default for p99)",
            file=sys.stderr,
        )

        # Parity part 2: sketch percentile must equal the exact selection.
        # Builds are row-local, so the check runs on the subsample directly —
        # re-running the full-fleet build just to slice it would add ~1s.
        topk_p99_sub, _peak = topk_step(sub_v, sub_c)
        topk_p99_sub = np.asarray(topk_p99_sub)
        check(
            "topk_sketch==exact",
            bool(np.array_equal(topk_p99_sub, exact_p99_sub)),
            f"max |Δ| = {np.max(np.abs(topk_p99_sub - exact_p99_sub))}",
        )

        spec = DigestSpec(gamma=1.01, min_value=1e-7, num_buckets=2560)

        @jax.jit
        def digest_step(values, counts):
            d = digest_ops.build_from_packed(spec, values, counts, chunk_size=chunk)
            return digest_ops.percentile(spec, d, 99.0), digest_ops.peak(d)

        digest_elapsed, digest_spread = timed(digest_step)
        secondary["digest_containers_per_sec"] = round(n / digest_elapsed, 1)
        print(
            f"bench: tdigest sketch (Pallas matmul-histogram) {digest_elapsed:.3f}s "
            f"(spread {digest_spread:.0f}%) -> {n / digest_elapsed:.0f} containers/s "
            f"(streaming/mergeable path)",
            file=sys.stderr,
        )

        # Parity part 3: digest honors its guaranteed relative error; the
        # tracked peak is exact (it is what memory recommendations use).
        digest_p99_sub, digest_peak_sub = digest_step(sub_v, sub_c)
        est = np.asarray(digest_p99_sub)
        rel = np.abs(est - exact_p99_sub) / np.maximum(exact_p99_sub, spec.min_value)
        bound = spec.relative_error * 1.05 + 1e-6  # bound + float slack
        check(
            "digest_error_bound",
            bool(np.all(rel <= bound)),
            f"max rel err = {np.max(rel):.5f} vs bound {bound:.5f}",
        )
        peak_sub = np.asarray(digest_peak_sub)
        want_peak = np.asarray(masked_max(sub_v, sub_c))
        check(
            "digest_peak==max",
            bool(np.array_equal(peak_sub, want_peak)),
            "peak mismatch",
        )

    if not os.environ.get("BENCH_SKIP_JOURNAL"):
        journal_leg(secondary)

    if not os.environ.get("BENCH_SKIP_OBS"):
        # Tracing-overhead gates (`krr_tpu.obs`): a parity-style failure here
        # (>2% traced overhead, or traced results not bit-exact) exits
        # nonzero like any other parity break. The scan-level leg covers the
        # whole Runner pipeline; the device leg isolates the staged compute
        # sub-spans + fencing added by `krr_tpu.obs.device`.
        obs_leg(secondary, check)
        obs_device_leg(secondary, check)
        # Sentinel gates (`krr_tpu.obs.sentinel` over `krr_tpu.obs.timeline`):
        # injected regressions on a synthetic timeline must be detected and
        # correctly attributed, a clean control must stay silent, and the
        # flight recorder's per-tick cost must stay under 2% of a scan wall.
        # Runs after obs_leg: the overhead gate reads its measured scan wall.
        sentinel_leg(secondary, check)

    if not os.environ.get("BENCH_SKIP_CHAOS"):
        # Chaos soak gates: degraded-publish semantics, recovery
        # bit-exactness, and the breaker-bounded hard-down tick wall — the
        # standing regression gate for the fault-isolation machinery.
        chaos_leg(secondary, check)

    if not os.environ.get("BENCH_SKIP_EVAL"):
        # Quality-evaluation gates: byte-identical repeated replays and the
        # labeled-archetype ranking contract (undersized probe finds the
        # declared OOM windows, oversized probe buys zero incidents with
        # more slack) — the standing gate for the eval scoreboard.
        eval_leg(secondary, check)

    if not os.environ.get("BENCH_SKIP_DISCOVERY"):
        # Discovery gates: the watch-mode reconcile must stay bit-identical
        # to a fresh relist through injected churn AND beat the relist wall
        # at equal fleet width — the O(churn) claim, measured.
        discovery_leg(secondary, check)

    if not os.environ.get("BENCH_SKIP_INGEST"):
        # Push-ingest gates: remote-write-fed serve vs the range-fetched
        # pull control — published results + resident store bit-exact,
        # steady-state push ticks issue zero range queries, and the push
        # tick wall beats the pull control's; decode samples/s ceiling
        # trended.
        ingest_leg(secondary, check)

    if not os.environ.get("BENCH_SKIP_FETCHPLAN"):
        # Adaptive fetch-engine gates: planner engagement (coalesce + shard
        # counters non-zero), bit-exactness vs the fixed-plan control, and
        # the AIMD autotuner seeing per-query verdicts.
        fetchplan_leg(secondary, check)

    if not os.environ.get("BENCH_SKIP_WIRE"):
        # Wire-shrink gates: compressed + downsampled scan bit-exact vs the
        # identity/raw control, with compression engagement and a measured
        # wire_compression_ratio > 1.
        wire_leg(secondary, check)

    if not os.environ.get("BENCH_SKIP_FEDERATION"):
        # Federation gates: N in-process shards streaming delta-WAL records
        # over real TCP into an aggregator serve — merged store bit-exact
        # vs the single-process control, aggregate fold cost and delta wire
        # bytes trended.
        federation_leg(secondary, check)

    if not os.environ.get("BENCH_SKIP_HA"):
        # HA + replica gates: key-range partitioned ring with a standby
        # takeover and duplicate injection (merged view bit-exact vs the
        # single-process control, zero lost epochs, exactly-once apply),
        # plus a read replica serving byte-identical responses at >= 90%
        # of its source aggregator's RPS.
        ha_leg(secondary, check)

    if not os.environ.get("BENCH_SKIP_FLEETOBS"):
        # Fleet-observability gates: the cross-process trace rings stitch
        # into one causally-joined component (scan → apply_record →
        # install), the per-epoch freshness lineage stays monotone with
        # every stage histogram engaged, and lineage stamping costs <2%
        # of the no-lineage control's tick wall while staying bit-exact.
        fleet_obs_leg(secondary, check)

    if not os.environ.get("BENCH_SKIP_READPATH"):
        # Read-path gates: concurrent keep-alive readers against a live
        # serve during scan ticks — steady-state cache hit rate, zero-render
        # 304s, pushdown bit-exactness, LRU bounds, and the cached-vs-
        # uncached RPS ratio; p99 trended round-over-round.
        readpath_leg(secondary, check)

    if not os.environ.get("BENCH_SKIP_STORE"):
        # Durable-store gates: delta append vs legacy full rewrite,
        # recovery-replay bit-exactness, and the SIGKILL kill-recover soak.
        store_leg(secondary, check)
        store_kill_leg(secondary, check)

    if not os.environ.get("BENCH_SKIP_E2E"):
        # End-to-end pipeline numbers (real Runner against the in-process
        # fakes + digest-ingest at a 100k synthetic fleet) from bench_e2e.py,
        # in a subprocess so a pipeline failure can't take down the headline.
        import subprocess

        env = {**os.environ}
        # Record the e2e number at fleet scale (round-2 verdict: >= 10k
        # containers) unless the caller pinned a size.
        env.setdefault("BENCH_E2E_CONTAINERS", "10000")
        script = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_e2e.py")

        def e2e_subprocess(tag: str, extra_env: dict, timeout: int) -> None:
            """One bench_e2e.py subprocess; a failure or timeout records a
            note under `tag` instead of sinking the headline or each other."""
            try:
                proc = subprocess.run(
                    [sys.executable, script],
                    capture_output=True,
                    text=True,
                    timeout=timeout,
                    env={**env, **extra_env},
                )
                for line in proc.stderr.splitlines():
                    print(line, file=sys.stderr)
                if proc.returncode == 0 and proc.stdout.strip():
                    payload = json.loads(proc.stdout.strip().splitlines()[-1])
                    if payload:
                        secondary.update(payload)
                    else:
                        # e.g. a stale exported BENCH_E2E_FLEET_ROWS=0 — record
                        # the skip instead of silently dropping the leg.
                        secondary[tag] = "skipped (env disabled this leg)"
                else:
                    secondary[tag] = f"failed rc={proc.returncode}"
            except Exception as e:
                secondary[tag] = f"failed: {e.__class__.__name__}"

        # Main legs (10k scans, 100k ingest/store, scanner throughputs) and
        # the ~15-minute FULL 100k-container scan run in SEPARATE
        # subprocesses: a timeout on the long fleet scan must not lose the
        # rest of the e2e numbers (or vice versa).
        # FLEET_ONLY is explicitly cleared on the main-legs call so an
        # operator's exported debug value can't silently hollow it out.
        e2e_subprocess(
            "e2e", {"BENCH_E2E_FLEET_ROWS": "0", "BENCH_E2E_FLEET_ONLY": "0"}, timeout=900
        )
        e2e_subprocess("fleet_e2e", {"BENCH_E2E_FLEET_ONLY": "1"}, timeout=1800)

    py_per_container = python_reference_seconds_per_container(t, py_sample)
    baseline_throughput = 1.0 / py_per_container
    print(
        f"bench: python-reference {py_per_container:.3f}s/container ({baseline_throughput:.2f}/s)",
        file=sys.stderr,
    )

    # Round-over-round gate on the STABLE metric (round-4 verdict item 4):
    # the raw single-dispatch rate swings ~12% with rig RTT, so a real
    # kernel regression hides inside its noise; the pipelined rate holds
    # ~1%. Compare this run's pipelined headline against the newest recorded
    # BENCH_r*.json and flag a >5% drop in one field.
    previous = _previous_round_stable()
    if previous is not None:
        prev_file, prev_rate = previous
        vs_previous = pipelined_throughput / prev_rate
        regression = vs_previous < 0.95
        print(
            f"bench: vs {prev_file} stable rate {prev_rate:.0f} -> x{vs_previous:.3f}"
            + (" REGRESSION (>5% below previous round)" if regression else ""),
            file=sys.stderr,
        )
        previous_fields = {
            "vs_previous_round": round(vs_previous, 3),
            "previous_round_file": prev_file,
            "previous_round_stable_rate": round(prev_rate, 1),
            "regression_vs_previous": regression,
        }
    else:
        # Same shape with or without a recorded previous round — gate
        # scripts read these fields unconditionally.
        previous_fields = {
            "vs_previous_round": None,
            "previous_round_file": None,
            "previous_round_stable_rate": None,
            "regression_vs_previous": False,
        }

    print(
        json.dumps(
            {
                # Headline = the latency-honest pipelined rate (spread ~1%;
                # the raw single-dispatch rate is carried as
                # raw_containers_per_sec, spread ~12% rig-RTT-bound).
                "metric": "containers_per_sec_exact_p99_7d_at_5s_pipelined",
                "value": round(pipelined_throughput, 1),
                "unit": "containers/s",
                "vs_baseline": round(pipelined_throughput / baseline_throughput, 1),
                "parity": "fail" if parity_failures else "ok",
                "runs": runs,
                "raw_containers_per_sec": round(throughput, 1),
                "raw_spread_pct": round(exact_spread, 1),
                "raw_vs_baseline": round(throughput / baseline_throughput, 1),
                "dispatch_floor_ms": round(floor * 1e3, 1),
                "pipelined_depth": pipeline_depth,
                "pipelined_spread_pct": round(pipe_spread, 1),
                "floor_corrected_containers_per_sec": (
                    round(floor_corrected, 1) if floor_corrected is not None else None
                ),
                **previous_fields,
                # The fetch-wall twin of the kernel gate: warm fleet-scan
                # fetch seconds vs the previous recorded round (same fleet
                # width only), >15% slower flags a regression.
                **_fetch_trendline_fields(secondary),
                # The read-path twin: loadtest p99 vs the previous recorded
                # round at the same readpath fleet width, >15% slower flags
                # a regression.
                **_readpath_trendline_fields(secondary),
                "secondary": secondary,
            }
        )
    )
    if parity_failures:
        print(f"bench: PARITY FAILURES: {parity_failures}", file=sys.stderr)
        sys.exit(1)


def _previous_round_payload():
    """(filename, parsed payload) of the newest recorded BENCH_r*.json, or
    None — the shared source of every round-over-round gate."""
    import glob
    import re

    here = os.path.dirname(os.path.abspath(__file__))
    newest, newest_round = None, -1
    for path in glob.glob(os.path.join(here, "BENCH_r*.json")):
        match = re.search(r"BENCH_r(\d+)\.json$", path)
        if match and int(match.group(1)) > newest_round:
            newest, newest_round = path, int(match.group(1))
    if newest is None:
        return None
    try:
        with open(newest) as f:
            payload = json.load(f)
        # The driver wraps the bench's own JSON line under "parsed".
        return os.path.basename(newest), payload.get("parsed", payload)
    except Exception:
        return None


def _previous_round_stable():
    """(filename, stable rate) from the newest recorded BENCH_r*.json, or
    None. Older rounds carried the raw rate as `value` with the pipelined
    rate in a secondary field; prefer the pipelined one wherever present."""
    previous = _previous_round_payload()
    if previous is None:
        return None
    prev_file, payload = previous
    try:
        stable = payload.get("pipelined_containers_per_sec") or payload.get("value")
        return prev_file, float(stable)
    except Exception:
        return None


def _fetch_trendline_fields(secondary: dict) -> dict:
    """The fleet-scan fetch-wall gate, mirroring the kernel-rate gate: this
    run's warm ``fleet_e2e_fetch_seconds`` vs the newest recorded round's.
    The threshold is 15% (wall-clock fetch on the shared rig wobbles more
    than the pipelined kernel rate's ~1%); a trip means the fetch leg —
    the ROADMAP's #1 wall — regressed and the round must not be recorded
    as healthy. Fields are emitted unconditionally so gate scripts can
    read them without probing."""
    fields = {
        "fetch_vs_previous_round": None,
        "previous_round_fetch_seconds": None,
        "fetch_regression_vs_previous": False,
        "wire_vs_previous_round": None,
        "previous_round_wire_mb": None,
        "wire_regression_vs_previous": False,
    }
    current = secondary.get("fleet_e2e_fetch_seconds")
    previous = _previous_round_payload()
    if previous is None or not isinstance(current, (int, float)) or current <= 0:
        return fields
    prev_file, payload = previous
    prev_secondary = payload.get("secondary") or {}
    prev_fetch = prev_secondary.get("fleet_e2e_fetch_seconds")
    if not isinstance(prev_fetch, (int, float)) or prev_fetch <= 0:
        return fields
    if prev_secondary.get("fleet_e2e_containers") != secondary.get("fleet_e2e_containers"):
        # Different fleet widths (e.g. a --smoke run vs a full round):
        # the ratio would read the scale, not the transport.
        return fields
    vs = current / prev_fetch  # >1 = slower than the previous round
    regression = vs > 1.15
    print(
        f"bench: fleet fetch {current}s vs {prev_file} {prev_fetch}s -> x{vs:.3f}"
        + (" FETCH REGRESSION (>15% above previous round)" if regression else ""),
        file=sys.stderr,
    )
    fields.update(
        {
            "fetch_vs_previous_round": round(vs, 3),
            "previous_round_fetch_seconds": prev_fetch,
            "fetch_regression_vs_previous": regression,
        }
    )
    # Wire-bytes twin of the fetch-seconds gate: at a pinned fleet width
    # the warm scan's wire MB is nearly deterministic, so growth past 15%
    # means compression silently fell back (or response volume grew) —
    # exactly the regression the compressed transport exists to prevent.
    current_wire = secondary.get("fleet_e2e_wire_mb")
    prev_wire = prev_secondary.get("fleet_e2e_wire_mb")
    if (
        isinstance(current_wire, (int, float)) and current_wire > 0
        and isinstance(prev_wire, (int, float)) and prev_wire > 0
    ):
        wire_vs = current_wire / prev_wire
        wire_regression = wire_vs > 1.15
        print(
            f"bench: fleet wire {current_wire} MB vs {prev_file} {prev_wire} MB "
            f"-> x{wire_vs:.3f}"
            + (
                " WIRE REGRESSION (>15% above previous round — compression fallback?)"
                if wire_regression
                else ""
            ),
            file=sys.stderr,
        )
        fields.update(
            {
                "wire_vs_previous_round": round(wire_vs, 3),
                "previous_round_wire_mb": prev_wire,
                "wire_regression_vs_previous": wire_regression,
            }
        )
    return fields


def _readpath_trendline_fields(secondary: dict) -> dict:
    """The read-path p99 gate, mirroring the fetch-wall one: this run's
    loadtest ``readpath_p99_ms`` vs the newest recorded round's at the SAME
    readpath fleet width (a smoke run must not compare against a full
    round). >15% slower flags ``readpath_regression_vs_previous`` — a cache
    wired out of the hot path or a render-pool misbound shows up here as a
    latency cliff, not a silent serving regression. Fields are emitted
    unconditionally so gate scripts can read them without probing."""
    fields = {
        "readpath_vs_previous_round": None,
        "previous_round_readpath_p99_ms": None,
        "readpath_regression_vs_previous": False,
    }
    current = secondary.get("readpath_p99_ms")
    previous = _previous_round_payload()
    if previous is None or not isinstance(current, (int, float)) or current <= 0:
        return fields
    prev_file, payload = previous
    prev_secondary = payload.get("secondary") or {}
    prev_p99 = prev_secondary.get("readpath_p99_ms")
    if not isinstance(prev_p99, (int, float)) or prev_p99 <= 0:
        return fields
    if prev_secondary.get("readpath_workloads") != secondary.get("readpath_workloads"):
        return fields
    vs = current / prev_p99  # >1 = slower than the previous round
    regression = vs > 1.15
    print(
        f"bench: readpath p99 {current} ms vs {prev_file} {prev_p99} ms -> x{vs:.3f}"
        + (" READPATH REGRESSION (>15% above previous round)" if regression else ""),
        file=sys.stderr,
    )
    fields.update(
        {
            "readpath_vs_previous_round": round(vs, 3),
            "previous_round_readpath_p99_ms": prev_p99,
            "readpath_regression_vs_previous": regression,
        }
    )
    return fields


if __name__ == "__main__":
    main()
