"""Strategy plugin boundary: settings, base class, and registry.

This preserves the reference's load-bearing design idea (SURVEY.md §1): a user
script that merely *defines* a ``BaseStrategy`` subclass and then calls
``krr_tpu.run()`` gets a new CLI sub-command for free, with the strategy's
pydantic settings fields surfaced as ``--flags``. Differences from the
reference implementation (`/root/reference/robusta_krr/core/abstract/strategies.py`):

* registration happens eagerly via ``__init_subclass__`` into an explicit
  registry (instead of walking ``__subclasses__()`` at call time);
* the CLI reflects settings fields programmatically (no ``exec`` templates);
* strategies get a **batched** entry point, ``run_batch(FleetBatch)``, which is
  where the TPU path lives. Plugins written against the reference's per-object
  ``run(history_data, object_data)`` contract still work: the default
  ``run_batch`` falls back to calling ``run`` per object.
"""

from __future__ import annotations

import abc
import datetime
from dataclasses import dataclass
from decimal import Decimal
from typing import Generic, Optional, TypeVar, get_args, get_origin

import pydantic as pd

from krr_tpu.models.allocations import ResourceType
from krr_tpu.models.objects import K8sObjectData
from krr_tpu.models.series import FleetBatch
from krr_tpu.obs.device import NULL_DEVICE_OBS, DeviceObs
from krr_tpu.utils.registry import PluginRegistry


@dataclass
class ResourceRecommendation:
    """Raw (pre-rounding) recommendation for one resource of one object."""

    request: Optional[Decimal]
    limit: Optional[Decimal]


#: Reference-shaped history: resource → pod → samples.
HistoryData = dict[ResourceType, dict[str, list[Decimal]]]
RunResult = dict[ResourceType, ResourceRecommendation]


class StrategySettings(pd.BaseModel):
    """Base settings every strategy inherits; fields become CLI flags.

    Defaults match the reference: two weeks of history at a 15-minute step
    (`/root/reference/robusta_krr/core/abstract/strategies.py:20-23`).
    """

    history_duration: float = pd.Field(24 * 7 * 2, ge=1, description="The duration of the history data to use (in hours).")
    timeframe_duration: float = pd.Field(15, ge=1, description="The step for the history data (in minutes).")

    @property
    def history_timedelta(self) -> datetime.timedelta:
        return datetime.timedelta(hours=self.history_duration)

    @property
    def timeframe_timedelta(self) -> datetime.timedelta:
        return datetime.timedelta(minutes=self.timeframe_duration)


_S = TypeVar("_S", bound=StrategySettings)

_STRATEGY_REGISTRY: PluginRegistry = PluginRegistry("strategy", "Strategy", "krr_tpu.strategies")


class BaseStrategy(abc.ABC, Generic[_S]):
    """Base class for recommendation strategies.

    Class attributes:
        __display_name__: CLI name; defaults to the class name with the
            ``Strategy`` postfix stripped, lowercased (``SimpleStrategy`` →
            ``simple``). Override explicitly to customize.
        row_chunkable: whether the Runner may split the fleet into row chunks
            (`run_batch_row_chunks`). True for row-local strategies (every
            built-in; also the per-object compat path by construction). Set
            False on a plugin whose ``run_batch`` looks across objects.
        stats_only_resources: resources this strategy consumes only through
            each pod's exact MAX (plus sample presence) — e.g. the
            reference's memory recommendation, max × 1.05. Sources that
            support it (the Prometheus loader) then ingest those resources
            through the cheaper stats route (no per-sample histogram work,
            no raw sample arrays) and the ragged history carries ONE
            synthetic sample per pod: its exact max. Results are identical
            for max-only consumers (max of per-pod maxes == max of all
            samples; pods without samples stay absent) while the packed
            device batch shrinks from [rows × T] to [rows × pods] — at
            fleet scale that removes the larger of the two host→device
            transfers entirely. True per-pod sample COUNTS are NOT
            preserved (every present pod reads as one sample), and
            per-sample values other than the max are gone — a plugin that
            consumes either for such a resource MUST override this back to
            ``frozenset()``.
    """

    __display_name__: str
    row_chunkable: bool = True
    stats_only_resources: "frozenset[ResourceType]" = frozenset()

    settings: _S

    def __init_subclass__(cls, **kwargs: object) -> None:
        super().__init_subclass__(**kwargs)
        # Register only concrete strategies (ones that implement `run`);
        # intermediate abstract bases stay out of the CLI, either by not
        # defining `run` or by opting out with `__register__ = False`.
        if cls.run is not BaseStrategy.run and cls.__dict__.get("__register__", True):
            _STRATEGY_REGISTRY.register(cls)

    #: Device-compute instrumentation (`krr_tpu.obs.device`): staged
    #: pack/digest/quantile/round sub-spans, compile attribution, padding
    #: gauges. The scan session swaps in its own wired instance
    #: (`ScanSession._wire_obs`); the class default keeps strategies built
    #: outside a session (plugins, unit tests) inert and import-cheap.
    obs: DeviceObs = NULL_DEVICE_OBS

    def __init__(self, settings: _S):
        self.settings = settings

    def __str__(self) -> str:
        return self.__display_name__.title()

    # ------------------------------------------------------------------ API
    @abc.abstractmethod
    def run(self, history_data: HistoryData, object_data: K8sObjectData) -> RunResult:
        """Per-object recommendation (reference-compatible plugin contract)."""

    def run_batch(self, batch: FleetBatch) -> list[RunResult]:
        """Fleet-wide recommendation. TPU-native strategies override this with
        a batched kernel; the default loops ``run`` per object (compat path
        for plugins written the reference way)."""
        return [self.run(batch.history_for(i), obj) for i, obj in enumerate(batch.objects)]

    # ----------------------------------------------------------- reflection
    @classmethod
    def find(cls, name: str) -> type["BaseStrategy"]:
        return _STRATEGY_REGISTRY.find(name)

    @classmethod
    def get_all(cls) -> dict[str, type["BaseStrategy"]]:
        return _STRATEGY_REGISTRY.get_all()

    @classmethod
    def get_settings_type(cls) -> type[StrategySettings]:
        """Recover the settings model from the generic parameter
        (``class MyStrategy(BaseStrategy[MySettings])``)."""
        for klass in cls.__mro__:
            for base in getattr(klass, "__orig_bases__", ()):
                origin = get_origin(base)
                if isinstance(origin, type) and issubclass(origin, BaseStrategy):
                    for arg in get_args(base):
                        if isinstance(arg, type) and issubclass(arg, StrategySettings):
                            return arg
        return StrategySettings


class BatchedStrategy(BaseStrategy[_S]):
    """Base for TPU-native strategies whose primary entry point is the batched
    kernel: subclasses implement ``run_batch`` and inherit a ``run`` that wraps
    one object into a singleton batch."""

    __register__ = False  # intermediate base — not a CLI strategy itself

    def run(self, history_data: HistoryData, object_data: K8sObjectData) -> RunResult:
        return self.run_batch(FleetBatch.from_history(history_data, object_data))[0]

    def profile_span(self):
        """Context manager tracing the device compute with ``jax.profiler``
        when the strategy's settings carry a ``profile_dir`` (SURVEY.md §5
        "tracing": the reference has none; the TPU-native equivalent is an
        xprof trace of the fleet kernels)."""
        import contextlib

        profile_dir = getattr(self.settings, "profile_dir", None)
        if not profile_dir:
            return contextlib.nullcontext()
        import jax

        return jax.profiler.trace(profile_dir)

    @abc.abstractmethod
    def run_batch(self, batch: FleetBatch) -> list[RunResult]:
        ...


def run_batch_row_chunks(
    strategy: "BaseStrategy", batch: FleetBatch, max_rows: int
) -> list[RunResult]:
    """Run ``strategy.run_batch`` over row chunks of at most ``max_rows``.

    Every built-in strategy is row-local (each object's recommendation
    depends only on its own samples), so chunked == unbatched exactly, while
    the packed [rows × T] copy is bounded to ``max_rows`` rows at a time —
    the fleet-axis analogue of the time-axis host streaming. Two details make
    the equality hold beyond mere row-locality: sub-batches pin the parent's
    packed capacity (`FleetBatch.row_slice`), so capacity-dependent decisions
    like tdigest's sketch cut-over can't vary with chunk boundaries; and a
    strategy that is NOT row-local can set ``row_chunkable = False`` to
    receive the whole fleet in one call regardless of ``max_rows``.

    Host-memory ceiling per chunk: ``max_rows × T × 4 B`` for the float32
    CPU pack plus ``max_rows × T × 8 B`` for the float64 memory pack (the
    ragged fetch buffers themselves are unaffected; for fleets whose *raw
    samples* exceed host memory, use the tdigest strategy's
    ``--digest_ingest``, which never materializes them).
    """
    if len(batch) <= max_rows or not getattr(strategy, "row_chunkable", True):
        return strategy.run_batch(batch)
    results: list[RunResult] = []
    for start in range(0, len(batch), max_rows):
        results.extend(strategy.run_batch(batch.row_slice(start, start + max_rows)))
    return results


AnyStrategy = BaseStrategy[StrategySettings]

__all__ = [
    "AnyStrategy",
    "BaseStrategy",
    "BatchedStrategy",
    "StrategySettings",
    "HistoryData",
    "RunResult",
    "ResourceRecommendation",
    "K8sObjectData",
    "ResourceType",
]
