"""Test configuration: force JAX onto a virtual 8-device CPU platform.

Real TPU hardware isn't available (or wanted) in unit tests; an 8-device CPU
mesh exercises the same sharding/collective code paths
(SURVEY.md §4 item 4). Must run before the first `import jax` anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the ambient env may point at a real TPU
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (xla_flags + " --xla_force_host_platform_device_count=8").strip()

# The image's sitecustomize imports jax and registers a TPU plugin before this
# conftest runs, so the env var alone is captured too late — override the
# already-initialized config as well.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng() -> np.random.Generator:
    return np.random.default_rng(1234)
