"""Global configuration — the two-level flag system.

Level 1 (this model) holds cluster/namespace selectors, value floors,
Prometheus settings, and logging flags, mirroring the reference's ``Config``
(`/root/reference/robusta_krr/core/models/config.py:18-65`) plus a TPU group.
Level 2 is the per-strategy ``StrategySettings`` carried as ``other_args`` and
reflected into CLI flags by ``krr_tpu.main``.

One deliberate divergence: the reference authenticates against kubeconfig at
*import* time (`config.py:10-15` — flagged as a boundary hazard in SURVEY.md
§3.1); here cluster detection is lazy and lives in the integrations layer.
"""

from __future__ import annotations

import os
from typing import Any, Literal, Optional, Union

import pydantic as pd
from pydantic import field_validator

from krr_tpu.utils.logging import KrrLogger


def detect_inside_cluster() -> bool:
    """True when running inside a pod with a service-account token mounted."""
    return bool(os.environ.get("KUBERNETES_SERVICE_HOST")) and os.path.exists(
        "/var/run/secrets/kubernetes.io/serviceaccount/token"
    )


#: Default per-window sample budget for streamed range queries — THE single
#: source of truth (the Config field default and the CLI flag default both
#: reference it; the fetch layer reads the Config field). Sits under
#: Prometheus's default --query.max-samples=50e6.
DEFAULT_MAX_STREAMED_SAMPLES = 40_000_000


class Config(pd.BaseModel):
    quiet: bool = False
    verbose: bool = False

    clusters: Union[list[str], Literal["*"], None] = None
    namespaces: Union[list[str], Literal["*"]] = "*"

    # Value settings
    cpu_min_value: int = pd.Field(5, ge=0)  # millicores
    memory_min_value: int = pd.Field(10, ge=0)  # megabytes

    # Prometheus settings
    prometheus_url: Optional[str] = None
    prometheus_auth_header: Optional[str] = None
    prometheus_ssl_enabled: bool = False
    prometheus_max_connections: int = pd.Field(32, ge=1)  # bulk-fetch fan-out width
    #: Per-window total-sample budget for STREAMED range queries (digest/stats
    #: native ingest — bodies never materialize, so this bounds the retry
    #: unit and the server-side load, not client memory). Default sits under
    #: Prometheus's default --query.max-samples=50e6; raise it alongside a
    #: raised server limit to fetch wide fleets in fewer windows.
    prometheus_max_streamed_samples: int = pd.Field(DEFAULT_MAX_STREAMED_SAMPLES, ge=1)
    #: Cap on one jittered exponential backoff sleep between range-query
    #: retry attempts: the 0.25 * 2^(n-1) ladder is bounded so deep ladders
    #: cannot balloon a scan's wall into minutes of sleeping.
    prometheus_backoff_cap_seconds: float = pd.Field(5.0, gt=0)
    #: Per-SCAN retry deadline budget: total seconds of retry-backoff sleep
    #: all of a scan's range queries may burn combined. Once spent, further
    #: transient failures fail terminally instead of retrying — a scan's
    #: wall stays bounded under a flapping backend. 0 disables the budget.
    prometheus_retry_deadline_seconds: float = pd.Field(60.0, ge=0)
    #: Circuit breaker around each Prometheus target: this many CONSECUTIVE
    #: retry-ladder exhaustions (transport errors / 5xx, never 4xx — a 4xx
    #: proves the target is alive; exhaustions whose ladder overlapped a
    #: sibling's success don't count either) open the breaker, after which
    #: queries fail in microseconds instead of burning a full backoff
    #: ladder each. The default sits above the exhaustion burst one broken
    #: namespace's fallback wave can produce, so only target-wide outages
    #: open it. 0 disables the breaker.
    prometheus_breaker_threshold: int = pd.Field(10, ge=0)
    #: Seconds an OPEN breaker fails fast before letting ONE probe query
    #: through (half-open): probe success closes the breaker, failure
    #: re-opens it for another cooldown.
    prometheus_breaker_cooldown_seconds: float = pd.Field(30.0, gt=0)

    # Adaptive fetch engine (`krr_tpu.core.fetchplan`)
    #: Query-plan shape for batched fleet fetches: "adaptive" coalesces
    #: small namespaces into one multi-namespace matcher query and shards
    #: giant ones across pod-regex partitions, shaped by the previous scan's
    #: per-query telemetry; "fixed" pins the classic one-query-per-
    #: (namespace, resource) shape — the escape hatch and the bit-exactness
    #: control (adaptive plans must match it exactly).
    fetch_plan: Literal["adaptive", "fixed"] = "adaptive"
    #: Series-count target for one planned query: a namespace expected to
    #: return ≥ 2× this many series shards; namespaces under a quarter of
    #: it become coalescing candidates. 0 (default) = auto: one sample-
    #: budget's worth per query (the route's samples budget ÷ the scan's
    #: window points), so a giant namespace shards into about the number of
    #: whole-range queries the sub-window fan-out would have split it into
    #: anyway — never more queries than the fixed plan.
    fetch_plan_target_series: int = pd.Field(0, ge=0)
    #: Most shards one giant namespace may split into.
    fetch_plan_max_shards: int = pd.Field(16, ge=1)
    #: AIMD-autotune the in-flight range-query limit between 1 and
    #: --prometheus-max-connections from live queue-wait/TTFB/failure
    #: signals (additive increase on healthy queued completions, halving on
    #: degraded TTFB or failed ladders); false pins the fixed-width
    #: semaphore at --prometheus-max-connections.
    fetch_autotune: bool = True
    #: Compressed transport for range-query responses: "auto" sends
    #: ``Accept-Encoding: gzip`` (zstd, gzip when a zstd module is
    #: importable) on both data planes and stream-decompresses into the
    #: native ingest (wire byte counters then report COMPRESSED bytes;
    #: decoded bytes report the post-inflate stream). "gzip" pins gzip
    #: even when zstd is available. "off" keeps today's identity requests
    #: byte-identical — the escape hatch and the wire-bench control.
    fetch_compression: Literal["auto", "gzip", "off"] = "auto"
    #: Server-side pre-aggregation for STATS-route range queries (the
    #: count+max ingest — the memory resource, and any stats_only
    #: strategy resource): "auto" rewrites eligible queries as
    #: max_over_time/count_over_time subqueries into grid-aligned coarse
    #: buckets so the server ships one value per bucket instead of every
    #: raw sample — bit-exact by construction (sum of bucket counts / max
    #: of bucket maxes equal the raw window's count/max), eligible only
    #: when the window start sits on the absolute step grid (serve aligns
    #: its window origin when this is on; one-shot scans engage when
    #: --scan-end-timestamp lands on the grid). The CPU digest route never
    #: downsamples — its per-value histogram needs every sample. Backends
    #: that reject subqueries fall back to the raw fetch automatically,
    #: per namespace, persistently. "off" disables the rewrite entirely.
    fetch_downsample: Literal["auto", "off"] = "off"
    #: Grid points per coarse downsample bucket. 0 = auto: up to 60,
    #: bounded so at least two full buckets fit the window and the coarse
    #: step survives the Prometheus duration format exactly.
    fetch_downsample_factor: int = pd.Field(0, ge=0)

    # Kubernetes settings
    kubeconfig: Optional[str] = None  # path override; default resolution in integrations

    # Logging settings
    format: str = "table"
    strategy: str = "simple"
    log_to_stderr: bool = False
    #: "console" = rich prefixed lines (the reference UX); "json" = one
    #: structured object per line carrying scan_id/span_id from the active
    #: trace span, so log lines join back to --trace / /debug/trace output.
    log_format: Literal["console", "json"] = "console"

    # Observability (`krr_tpu.obs`)
    #: Write a Chrome trace-event JSON (chrome://tracing / Perfetto) of the
    #: scan's spans to this file at exit. None = tracing stays the no-op
    #: tracer on the CLI hot path (serve always records into its ring for
    #: GET /debug/trace).
    trace_path: Optional[str] = None
    #: Completed scan traces the in-memory ring retains (serve's
    #: GET /debug/trace window; also the CLI export buffer).
    trace_ring_scans: int = pd.Field(16, ge=1)
    #: Write a Prometheus text-exposition snapshot of the scan's metrics
    #: registry to this file at exit (the CLI twin of serve's GET /metrics).
    metrics_dump_path: Optional[str] = None
    #: Exit nonzero when any object's fetch failed terminally (rows rendered
    #: UNKNOWN) — CI/cron scans must not mistake a half-fetched fleet for a
    #: clean run.
    strict: bool = False
    #: Log a warning for any Prometheus range query slower than this many
    #: seconds (retries included); 0 disables the slow-query log.
    prometheus_slow_query_seconds: float = pd.Field(10.0, ge=0)
    #: Write a one-shot SLO evaluation (`krr_tpu.obs.health` — the same
    #: objectives `krr-tpu serve` exposes on GET /statusz, evaluated once
    #: over this scan's registry) as JSON to this file at exit.
    statusz_path: Optional[str] = None
    #: Write the scan's critical-path attribution report
    #: (`krr_tpu.obs.profile` — the JSON `krr-tpu analyze` and serve's
    #: GET /debug/profile produce) to this file at exit. Implies a
    #: recording tracer, like --trace.
    profile_path: Optional[str] = None

    # SLO engine (`krr_tpu.obs.health`) — serve evaluates per scheduler
    # tick; one-shot scans evaluate once for --statusz.
    #: Error budget for the scan-failure objective: the fraction of scans
    #: allowed to abort before the budget burns.
    slo_scan_failure_budget: float = pd.Field(0.05, gt=0, le=1)
    #: Error budget for the fetch failed-row objective: the fraction of
    #: object fetches allowed to fail terminally (rows rendered UNKNOWN).
    slo_fetch_failure_budget: float = pd.Field(0.05, gt=0, le=1)
    #: Scan-latency objective limit: a scan's wall must fit this many
    #: seconds. 0 = auto: the serve scan cadence (a scan that can't fit its
    #: own interval is falling behind by construction).
    slo_scan_latency_seconds: float = pd.Field(0.0, ge=0)
    #: Freshness objective limit: the published window may age this many
    #: seconds before evaluations count as bad. 0 = auto: three scan
    #: cadences (aligned with /healthz's stale threshold).
    slo_freshness_seconds: float = pd.Field(0.0, ge=0)
    #: Burn-rate windows: the FAST window makes detection quick, the SLOW
    #: window keeps a brief blip from alerting — an alert fires only while
    #: both windows burn past their thresholds.
    slo_fast_window_seconds: float = pd.Field(300.0, gt=0)
    slo_slow_window_seconds: float = pd.Field(3600.0, gt=0)
    #: Burn-rate thresholds (windowed bad ratio ÷ budget; 1.0 = consuming
    #: exactly the budget). With the default 5% budgets a full outage burns
    #: at 20×, so 10/5 fires within a few ticks and resolves at
    #: fast-window speed.
    slo_fast_burn: float = pd.Field(10.0, gt=0)
    slo_slow_burn: float = pd.Field(5.0, gt=0)

    # Kubernetes discovery
    #: One pods request per namespace with client-side selector matching
    #: (O(namespaces) apiserver calls); False = the reference's per-workload
    #: server-side selector queries.
    bulk_pod_discovery: bool = True

    #: Inventory maintenance strategy: "relist" re-fetches every workload
    #: kind and pod index per discovery round (the classic shape — request
    #: shapes byte-identical to previous releases); "watch" keeps a resident
    #: inventory fed by Kubernetes watch streams (one list+watch per
    #: workload kind plus metadata-only pod watches per active namespace,
    #: with resourceVersion bookmarks) so each discovery tick is an
    #: in-memory O(churn) reconcile — the relist remains the cold-start
    #: seed and the 410/desync resync path. Watch mode always resolves
    #: pods client-side (the bulk-discovery selection path).
    discovery_mode: Literal["relist", "watch"] = "relist"
    #: Watch-mode ground-truth audit cadence: every this many seconds a
    #: FULL relist diffs the watched inventory against the apiserver —
    #: divergence is logged, counted
    #: (``krr_tpu_discovery_verify_divergences_total``), and repaired by
    #: adopting the relist. 0 = auto: four discovery intervals.
    discovery_verify_interval_seconds: float = pd.Field(0.0, ge=0)
    #: Where the watch-mode inventory snapshot (+ resourceVersions) persists
    #: so a warm restart skips the cold relist. None = serve derives
    #: ``discovery-inventory.json`` inside the sharded state directory
    #: (``<state_path>.discovery-inventory.json`` beside a legacy file);
    #: standalone loaders without a state path keep the inventory
    #: memory-only.
    discovery_snapshot_path: Optional[str] = None

    # Push-based metrics ingest (`krr_tpu.ingest`)
    #: How serve ticks get their samples. "pull" issues Prometheus range
    #: queries every tick (the classic shape). "push" runs a remote-write
    #: listener and folds buffered samples at tick time — a steady-state
    #: tick issues ZERO range queries; the range path remains the cold-start
    #: seed, the per-series-watermark gap backfill, and the periodic
    #: divergence audit's ground truth.
    metrics_mode: Literal["pull", "push"] = "pull"
    #: Remote-write listener bind port (push mode). 0 = ephemeral (tests;
    #: the chosen port is logged and shown on /statusz).
    ingest_port: int = pd.Field(9201, ge=0, le=65535)
    #: Push-mode ground-truth audit cadence: every this many seconds the
    #: tick's push-fed windows are ALSO range-fetched and compared row for
    #: row — divergence is logged, counted
    #: (``krr_tpu_ingest_verify_divergences_total``), and repaired by
    #: adopting the range rows and invalidating the diverged series buffers.
    #: 0 = auto: four scan intervals. Mirrors the discovery audit's ladder.
    ingest_verify_interval_seconds: float = pd.Field(0.0, ge=0)
    #: Largest accepted remote-write POST body (compressed bytes); larger
    #: declarations are refused with 413 before the body is read.
    ingest_max_body_bytes: int = pd.Field(16 << 20, gt=0)
    #: Staleness horizon for grid evaluation: a grid point takes the newest
    #: buffered sample no older than this (the Prometheus staleness default,
    #: so push folds see what a range query would have returned).
    ingest_lookback_seconds: float = pd.Field(300.0, gt=0)
    #: Per-series buffer cap; overflow sheds the oldest samples (counted)
    #: and pulls the series' completeness watermark forward so affected
    #: windows fall back to the range path instead of folding short.
    ingest_max_samples_per_series: int = pd.Field(8192, gt=0)
    #: Resident series cap: new series beyond it are rejected (counted) —
    #: a mislabeled fleet can't balloon the plane.
    ingest_max_series: int = pd.Field(500_000, gt=0)

    #: One Prometheus range query per (namespace, resource) with client-side
    #: (pod, container) routing — O(namespaces) round trips; False = one query
    #: per (workload, resource). A failed batched query falls back to the
    #: per-workload path for its namespace automatically, so this flag exists
    #: for backends where namespace-sized responses are pathological (huge
    #: mono-namespace fleets behind a slow proxy).
    batched_fleet_queries: bool = True

    #: Pin the scan window's right edge to an absolute unix timestamp —
    #: reproducible scans (two runs see identical samples) and offline
    #: benchmarking against recorded history. Default: now.
    scan_end_timestamp: Optional[float] = None

    #: Scan-pipeline depth (`krr_tpu.core.pipeline`): digest-ingest scans
    #: fetch the fleet as per-namespace batches and fold each batch while
    #: the rest still fetch, with at most this many batches in flight at
    #: each of the fetch and the fold-queue stages (bounded backpressure:
    #: ≤ 2 × depth + 1 fetched-but-unfolded batches ever exist). 0 disables
    #: streaming — the staged gather-then-fold path, kept for A/B timing
    #: and as an escape hatch.
    pipeline_depth: int = pd.Field(4, ge=0)

    # Server (`krr-tpu serve`) settings
    server_host: str = "127.0.0.1"
    #: 0 = an ephemeral port (tests; the chosen port is logged).
    server_port: int = pd.Field(8080, ge=0, le=65535)
    #: Seconds between incremental delta scans (each fetches only the window
    #: since the last fold).
    scan_interval_seconds: float = pd.Field(900.0, gt=0)
    #: Seconds between fleet re-discoveries (workload churn pickup + store
    #: compaction); effectively rounded up to the scan cadence, since
    #: discovery staleness is checked at each scan tick.
    discovery_interval_seconds: float = pd.Field(3600.0, gt=0)
    #: Degraded-tick floor: a serve tick whose fetch-success fraction falls
    #: BELOW this percentage aborts (nothing folds, the window refetches
    #: next tick) instead of publishing a mostly-empty fleet — a mostly-dead
    #: Prometheus must not publish garbage. At or above it, failed workloads
    #: quarantine (carry forward last-good digests, marked stale) and the
    #: successful remainder still folds and publishes. 100 restores the
    #: all-or-nothing pre-quarantine behavior.
    min_fetch_success_pct: float = pd.Field(50.0, ge=0, le=100)
    # High-QPS read path (`krr_tpu.server.state.ResponseCache` + the app's
    # bounded render pool).
    #: Epoch-keyed rendered-response cache for GET /recommendations: False
    #: restores the render-per-request behavior (the bench loadtest's
    #: uncached control, and an escape hatch).
    response_cache_enabled: bool = True
    #: Entry bound on the response cache — one entry per (format,
    #: canonicalized filters, page, encoding) combination, evicted LRU.
    response_cache_max_entries: int = pd.Field(256, ge=1)
    #: Byte budget (MiB) on cached response bodies — adversarial filter
    #: cardinality must not OOM the server.
    response_cache_max_mb: float = pd.Field(64.0, gt=0)
    #: Concurrent cache-miss renders (worker threads) the read path allows.
    server_render_concurrency: int = pd.Field(4, ge=1)
    #: Requests allowed to WAIT behind a saturated render pool before the
    #: rest shed with 503/Retry-After (0 = shed as soon as every worker is
    #: busy).
    server_render_queue: int = pd.Field(16, ge=0)
    #: Read-path latency SLO: the per-tick GET /recommendations p99 must
    #: stay under this many seconds (threshold objective, like
    #: scan_latency). 0 disables the objective.
    slo_read_p99_seconds: float = pd.Field(0.0, ge=0)

    # Durable digest store (`krr_tpu.core.durastore`) — the sharded
    # state-directory persistence behind the strategy's --state_path (the
    # on-disk FORMAT is the strategy's --store_format; these tune the
    # sharded engine).
    #: Rows per base-snapshot shard file: compaction slices the store into
    #: contiguous row ranges of this size.
    store_shard_rows: int = pd.Field(32768, ge=1)
    #: Compaction trigger: fold the delta WAL back into base shards once it
    #: exceeds this fraction of the base snapshots' bytes (replay time
    #: stays bounded while the per-tick persist stays one small append).
    store_compact_wal_ratio: float = pd.Field(0.5, gt=0)
    #: Compaction floor in MiB: below this WAL size, never compact — tiny
    #: stores must not pay a base rewrite per handful of ticks.
    store_compact_min_wal_mb: float = pd.Field(16.0, ge=0)

    # Scan flight recorder + regression sentinel (`krr_tpu.obs.timeline`,
    # `krr_tpu.obs.sentinel`) — serve-only: each completed tick appends one
    # durable timeline record, and the sentinel classifies it against
    # rolling median/MAD baselines.
    #: Timeline file override. None = derive from the strategy's state_path
    #: (``<state_dir>/timeline.log`` in a sharded state directory,
    #: ``<state_path>.timeline`` beside a legacy single file); an explicit
    #: empty string keeps the recorder memory-only even with a state_path.
    timeline_path: Optional[str] = None
    #: Scan records the recorder retains (in memory and, via retention
    #: compaction, on disk).
    timeline_retain_records: int = pd.Field(4096, ge=1)
    #: The --no-sentinel escape hatch: False records the timeline without
    #: classifying it.
    sentinel_enabled: bool = True
    #: Nominal scans of a kind (full|delta) the sentinel must observe
    #: before issuing verdicts for that kind — a cold server must not page
    #: on its first tick.
    sentinel_warmup_scans: int = pd.Field(8, ge=2)
    #: Rolling baseline window: nominal values per (kind, category) the
    #: median/MAD bands are computed over. Also the consecutive-regression
    #: count after which a sustained level shift rebases as the new normal.
    sentinel_baseline_scans: int = pd.Field(64, ge=2)
    #: Deviation threshold in band units: a category regresses when its
    #: value exceeds ``median + sigma × max(1.4826·MAD, floors)``.
    sentinel_sigma: float = pd.Field(3.0, gt=0)
    #: Relative band floor as a fraction of the median — keeps a
    #: near-constant series (MAD ≈ 0) from flagging noise.
    sentinel_rel_floor: float = pd.Field(0.10, ge=0)
    #: Absolute band floor in seconds (same purpose, for tiny medians).
    sentinel_abs_floor_seconds: float = pd.Field(0.05, ge=0)
    #: Register the optional ``scan_regressions`` SLO objective: regressed
    #: scans burn its error budget like aborted scans burn scan_failures'.
    sentinel_slo_enabled: bool = False
    #: Error budget for that objective: the fraction of classified scans
    #: allowed to regress before the budget burns.
    sentinel_slo_budget: float = pd.Field(0.10, gt=0, le=1)

    # Multi-cluster federation (`krr_tpu.federation`)
    #: ``host:port`` the serve process accepts scanner-shard delta streams
    #: on — setting it turns serve into the federation AGGREGATOR: the
    #: scheduler stops scanning and each tick replays queued shard records
    #: into the fleet store instead, publishing the merged view through the
    #: unchanged read path. None = classic single-process serve.
    federation_listen: Optional[str] = None
    #: ``host:port`` of the aggregator a ``krr-tpu shard`` process streams
    #: its delta records to.
    federation_aggregator: Optional[str] = None
    #: Shard identity in the federation (epoch watermarks key on it).
    #: Default: the shard's configured cluster list joined with '/'.
    federation_shard_id: Optional[str] = None
    #: Shard staleness budget at the aggregator: a shard whose newest
    #: delivered window is older than this serves carried-forward rows with
    #: ``stale_since`` marks (the federation twin of the quarantine marks).
    #: 0 = auto: three scan cadences.
    federation_staleness_seconds: float = pd.Field(0.0, ge=0)
    #: Record-count bound on BOTH sides of the federation stream: the
    #: aggregator queues at most this many decoded-but-unapplied records
    #: per shard before back-pressuring that shard's connection, and a
    #: shard whose unacked buffer exceeds it collapses the backlog into
    #: one snapshot record (bounded memory through an aggregator outage
    #: of any length).
    federation_queue_records: int = pd.Field(4096, ge=1)
    #: Key-range partitioned aggregation plane
    #: (`krr_tpu.federation.ring`): ``name=host:port[|host:port...],...``
    #: names each aggregator and its endpoint(s) — a shard splits every
    #: tick's delta record by consistent-hash key owner and streams each
    #: partition to its owning aggregator; a node listing extra endpoints
    #: replicates its stream to standbys (HA failover with zero lost
    #: epochs). Mutually exclusive with ``federation_aggregator`` on a
    #: shard (the ring subsumes the single-aggregator case).
    federation_ring: Optional[str] = None
    #: Ceiling on the federation reconnect backoff ladder (uplinks AND
    #: replica feeds): waits grow 0.25·2^(n−1) seconds, capped here before
    #: ±50% jitter — the same retry semantics as
    #: ``prometheus_backoff_cap_seconds``.
    federation_backoff_cap_seconds: float = pd.Field(5.0, gt=0)
    #: ``host:port`` of a HIGHER-tier aggregator this serve process
    #: uplinks its OWN store's deltas to (requires ``federation_listen``):
    #: region aggregators uplink to a global one over the same shard
    #: protocol, so the tiers compose without a second wire format.
    federation_uplink: Optional[str] = None
    #: End-to-end freshness lineage: when on, every shard tick stamps its
    #: delta records with a lineage block (newest-sample → fold → apply →
    #: publish → install timestamps accumulate hop by hop) and the
    #: aggregator fires ``krr_tpu_e2e_freshness_seconds{stage}`` per epoch.
    #: Metadata-only — stores and served bytes are bit-identical either
    #: way. Off = the no-lineage control (bench overhead gate).
    federation_lineage_enabled: bool = True

    #: One-shot recovery flag for ``--fetch-downsample`` over a persisted
    #: window cursor that predates the flag (unaligned grid): drop the
    #: cursor and accumulated rows at startup so the next tick runs a
    #: grid-ALIGNED full backfill and downsampling actually engages.
    realign_window_grid: bool = False

    #: Staleness budget for quarantined workloads: how old a quarantined
    #: workload's last folded sample may grow while its digests carry
    #: forward. Past the budget the workload's accumulated row is dropped
    #: and it re-enters as fresh (full-window backfill on the next
    #: successful fetch) — incremental catch-up that far back would exceed
    #: what the operator is willing to serve as "last known good".
    #: 0 = auto: ten scan cadences.
    max_staleness_seconds: float = pd.Field(0.0, ge=0)

    # Recommendation history + hysteresis (`krr_tpu.history`, serve publish path)
    #: Journal file recording every recompute's raw recommendations (the
    #: flight recorder behind GET /history, GET /drift, and `krr-tpu diff`).
    #: None = derive ``<state_path>.journal`` when the strategy's state_path
    #: is set, else keep the journal memory-only; an explicit empty string
    #: forces memory-only even with a state_path.
    history_path: Optional[str] = None
    #: Journal retention window — records older than this are dropped by the
    #: per-tick compaction, bounding journal growth at fleet scale.
    history_retention_seconds: float = pd.Field(7 * 24 * 3600.0, gt=0)
    #: Hysteresis dead band: a workload's published recommendation holds
    #: until the raw recommendation drifts more than this percentage from
    #: it (relative, per resource)...
    hysteresis_dead_band_pct: float = pd.Field(5.0, ge=0)
    #: ...for this many CONSECUTIVE scan ticks (then it jumps straight to
    #: the current raw value).
    hysteresis_confirm_ticks: int = pd.Field(2, ge=1)
    #: The --no-hysteresis escape hatch: False publishes every recompute
    #: verbatim (bit-exact legacy behavior); the journal still records
    #: every tick either way.
    hysteresis_enabled: bool = True

    # Quality evaluation (`krr_tpu.eval`)
    #: Replay ticks `krr-tpu eval` walks the recorded grid in: each tick the
    #: strategy sees the history so far and its raw recommendation routes
    #: through the real hysteresis gate before scoring.
    eval_replay_ticks: int = pd.Field(16, ge=1)
    #: Serve the journal-derived fleet savings block on GET /statusz (and
    #: the krr_tpu_eval_* gauges it refreshes); False skips the computation
    #: entirely on scrape.
    savings_enabled: bool = True

    # TPU backend settings
    #: Fleet-axis host chunking: the raw path's packed [rows × T] copy is
    #: built (and run) at most this many rows at a time
    #: (`krr_tpu.strategies.base.run_batch_row_chunks`).
    max_fleet_rows_per_device: int = pd.Field(200_000, ge=1)

    #: Persistent XLA compilation cache directory: a fresh process's first
    #: scan reuses compiled device programs from earlier processes instead
    #: of paying trace+compile again (the measured cold-start minute at
    #: fleet scale). Empty string disables.
    jax_compilation_cache_dir: str = "~/.cache/krr_tpu/jax-cache"

    other_args: dict[str, Any] = pd.Field(default_factory=dict)

    @field_validator("namespaces")
    @classmethod
    def _empty_namespaces_mean_all(cls, v: Union[list[str], Literal["*"]]) -> Union[list[str], Literal["*"]]:
        return "*" if v == [] else v

    @field_validator("strategy")
    @classmethod
    def _strategy_exists(cls, v: str) -> str:
        from krr_tpu.strategies.base import BaseStrategy

        BaseStrategy.find(v)  # raises with the available list if unknown
        return v

    @field_validator("format")
    @classmethod
    def _format_exists(cls, v: str) -> str:
        from krr_tpu.formatters.base import BaseFormatter

        BaseFormatter.find(v)
        return v

    @property
    def inside_cluster(self) -> bool:
        return detect_inside_cluster()

    def create_strategy(self):
        from krr_tpu.strategies.base import BaseStrategy

        strategy_type = BaseStrategy.find(self.strategy)
        settings_type = strategy_type.get_settings_type()
        return strategy_type(settings_type(**self.other_args))

    def create_logger(self) -> KrrLogger:
        return KrrLogger(
            quiet=self.quiet,
            verbose=self.verbose,
            log_to_stderr=self.log_to_stderr,
            log_format=self.log_format,
        )

    def create_tracer(self):
        """A recording tracer when ``--trace`` or ``--profile`` asked for
        one (both consume the recorded ring at exit), else the no-op
        tracer — the disabled path must stay free (`krr_tpu.obs.trace`).
        Serve swaps in a recording tracer unconditionally (its ring backs
        ``GET /debug/trace``)."""
        from krr_tpu.obs.trace import NULL_TRACER, Tracer

        if self.trace_path or self.profile_path:
            return Tracer(ring_scans=self.trace_ring_scans)
        return NULL_TRACER
