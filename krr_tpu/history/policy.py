"""The hysteresis gate: dead-band + confirmation filtering on the publish path.

Percentile recommendations over a noisy-but-stationary fleet wiggle
tick-to-tick; publishing every wiggle means every consumer of
``GET /recommendations`` sees constant churn it cannot act on (and a fleet
that APPLIES recommendations would thrash restarts). The gate makes the
published snapshot stable by construction:

* each workload's published value only moves when the RAW recommendation
  drifts more than ``dead_band_pct`` away from it (relative, per resource)
  for ``confirm_ticks`` CONSECUTIVE scan ticks;
* when the gate opens, the published value jumps straight to the current
  raw value (no smoothing — recommendations stay real samples, not
  synthetic averages);
* a workload's first tick always publishes (there is nothing to hold).

The gate holds the strategy's RAW outputs (CPU percentile cores, peak
memory MB pre-buffer) as float32 — substituting a held value through
``finalize_fleet`` reproduces the original published Decimals bit-exactly,
and re-seeding from the journal after a restart is equally exact.
``enabled=False`` (the ``--no-hysteresis`` escape hatch) passes the input
arrays through UNTOUCHED — same array objects, bit-exact legacy publish
behavior — while still tracking churn so the metric stays meaningful.

A workload absent from a tick (real churn: deleted, or filtered out of
discovery) loses its gate state; if it reappears, its first tick publishes
fresh. Discovery holds its inventory stable between re-discoveries, so this
only triggers on actual fleet changes.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

_EPS = 1e-12


@dataclass
class GateDecision:
    """One tick's gate output, aligned to the input key order.

    ``cpu``/``mem`` are the values to publish; ``published`` marks rows
    whose raw value became the published one (the journal's flag);
    ``changed`` marks previously-seen rows whose published value moved (the
    churn metric); ``suppressed`` marks out-of-band rows the gate withheld.
    """

    cpu: np.ndarray
    mem: np.ndarray
    published: np.ndarray
    changed: np.ndarray
    suppressed: np.ndarray
    out_of_band: np.ndarray


def _rel_drift_pct(raw: np.ndarray, held: np.ndarray) -> np.ndarray:
    """Relative drift of ``raw`` vs ``held`` in percent. NaN raw → 0 (no
    data moves nothing); finite raw over NaN held → inf (nothing held, must
    publish)."""
    raw64 = np.asarray(raw, dtype=np.float64)
    held64 = np.asarray(held, dtype=np.float64)
    out = np.zeros(len(raw64))
    finite_raw = np.isfinite(raw64)
    finite_held = np.isfinite(held64)
    both = finite_raw & finite_held
    out[both] = 100.0 * np.abs(raw64[both] - held64[both]) / np.maximum(np.abs(held64[both]), _EPS)
    out[finite_raw & ~finite_held] = np.inf
    return out


def _neq(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Elementwise inequality treating NaN == NaN (both-missing is not a change)."""
    return (a != b) & ~(np.isnan(a) & np.isnan(b))


class HysteresisGate:
    """Per-workload dead-band gate state, vectorized over the fleet."""

    def __init__(self, dead_band_pct: float = 5.0, confirm_ticks: int = 2, *, enabled: bool = True):
        self.dead_band_pct = float(dead_band_pct)
        self.confirm_ticks = int(confirm_ticks)
        self.enabled = bool(enabled)
        self._keys: tuple[str, ...] = ()
        self._index: dict[str, int] = {}
        self._held_cpu = np.empty(0, np.float32)
        self._held_mem = np.empty(0, np.float32)
        self._streak = np.empty(0, np.int32)
        self._seen = np.empty(0, bool)

    def seed(self, keys: list[str], cpu: np.ndarray, mem: np.ndarray) -> None:
        """Install trailing published baselines (restart resume from the
        journal): workloads arrive already-seen, so the first post-restart
        tick gates against the pre-restart published values instead of
        re-publishing the whole fleet."""
        self._keys = tuple(keys)
        self._index = {key: i for i, key in enumerate(self._keys)}
        self._held_cpu = np.asarray(cpu, dtype=np.float32).copy()
        self._held_mem = np.asarray(mem, dtype=np.float32).copy()
        self._streak = np.zeros(len(self._keys), np.int32)
        self._seen = np.isfinite(self._held_cpu) | np.isfinite(self._held_mem)

    def _align(self, keys: tuple[str, ...]) -> None:
        """Re-key the state arrays to this tick's fleet (no-op on the common
        stable-inventory tick)."""
        if keys == self._keys:
            return
        n = len(keys)
        held_cpu = np.full(n, np.nan, np.float32)
        held_mem = np.full(n, np.nan, np.float32)
        streak = np.zeros(n, np.int32)
        seen = np.zeros(n, bool)
        for i, key in enumerate(keys):
            j = self._index.get(key)
            if j is not None:
                held_cpu[i] = self._held_cpu[j]
                held_mem[i] = self._held_mem[j]
                streak[i] = self._streak[j]
                seen[i] = self._seen[j]
        self._keys = keys
        self._index = {key: i for i, key in enumerate(keys)}
        self._held_cpu, self._held_mem = held_cpu, held_mem
        self._streak, self._seen = streak, seen

    def observe(self, keys: list[str], cpu: np.ndarray, mem: np.ndarray) -> GateDecision:
        """One tick: fold the raw recommendations through the gate and
        return what to publish."""
        key_tuple = tuple(keys)
        cpu = np.asarray(cpu)
        mem = np.asarray(mem)
        self._align(key_tuple)
        n = len(key_tuple)

        if not self.enabled:
            # Bit-exact pass-through (same arrays out), with churn tracking
            # so krr_tpu_recommendation_churn_total measures the raw flap
            # rate the gate would otherwise absorb.
            raw_cpu32 = cpu.astype(np.float32, copy=False)
            raw_mem32 = mem.astype(np.float32, copy=False)
            changed = self._seen & (_neq(raw_cpu32, self._held_cpu) | _neq(raw_mem32, self._held_mem))
            self._held_cpu = raw_cpu32.copy()
            self._held_mem = raw_mem32.copy()
            self._seen = np.ones(n, bool)
            self._streak = np.zeros(n, np.int32)
            return GateDecision(
                cpu=cpu,
                mem=mem,
                published=np.ones(n, bool),
                changed=changed,
                suppressed=np.zeros(n, bool),
                out_of_band=np.zeros(n, bool),
            )

        cpu32 = cpu.astype(np.float32, copy=False)
        mem32 = mem.astype(np.float32, copy=False)
        drift = np.maximum(
            _rel_drift_pct(cpu32, self._held_cpu), _rel_drift_pct(mem32, self._held_mem)
        )
        out = drift > self.dead_band_pct
        self._streak = np.where(out, self._streak + 1, 0).astype(np.int32)
        opened = (~self._seen) | (self._streak >= self.confirm_ticks)
        changed = opened & self._seen
        # Publishing takes the raw value where it exists; a NaN resource
        # keeps its held value (an UNKNOWN tick must not erase a good one).
        new_cpu = np.where(opened & np.isfinite(cpu32), cpu32, self._held_cpu)
        new_mem = np.where(opened & np.isfinite(mem32), mem32, self._held_mem)
        suppressed = out & ~opened
        self._streak[opened] = 0
        # A row only counts as seen once it holds SOMETHING — an all-NaN
        # first tick must not make the first real value wait out the
        # confirmation window.
        self._seen = self._seen | (opened & (np.isfinite(new_cpu) | np.isfinite(new_mem)))
        self._held_cpu, self._held_mem = new_cpu, new_mem
        return GateDecision(
            cpu=new_cpu,
            mem=new_mem,
            published=opened,
            changed=changed,
            suppressed=suppressed,
            out_of_band=out,
        )
