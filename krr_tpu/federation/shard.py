"""The scanner shard: one cluster's discover→fetch→fold, streamed as deltas.

A :class:`FederatedShard` is the serve scheduler's scan half without the
serve half: it owns a private :class:`~krr_tpu.core.streaming.DigestStore`
with delta capture ON, runs the existing discover → fetch → fold pipeline
(`krr_tpu.core.runner.ScanSession`) over ITS clusters on the same
grid-clamped window math the scheduler uses, and after each fold encodes
the tick's captured mutation ops into WAL-format records
(`krr_tpu.core.durastore.encode_ops`) streamed to the aggregation plane
(`krr_tpu.federation.protocol`).

The aggregation plane is one or many: without ``--federation-ring`` the
shard streams every record to the single ``--aggregator`` endpoint;
with a ring (`krr_tpu.federation.ring`) it splits each tick's captured
ops by owning aggregator and streams each partition over its OWN
:class:`Uplink` with independent epoch watermarks — and a ring node that
names standby endpoints gets the same records on every endpoint (a
replicated WAL on the wire), so a standby takes over the key range with
zero lost epochs.

Delivery discipline (the exactly-once half the shard owns), per uplink:

* every tick's record appends to an UNACKED buffer before it is sent; the
  buffer only drops records the aggregator has ACKED (records are already
  sparse-encoded bytes, so the buffer costs roughly one WAL delta per
  unacked tick — and ring endpoints of one node SHARE the frame bytes);
* a lost connection just marks the stream down — ticks keep scanning and
  buffering; the next pump reconnects (capped jittered backoff, so N
  shards don't thundering-herd a restarted aggregator's handshake),
  handshakes, and re-sends everything past that endpoint's acked epoch
  (duplicates on the wire are discarded deterministically by the
  aggregator's epoch watermark);
* an endpoint whose WELCOME acked epoch is BEHIND what the shard already
  pruned (a standby that took over mid-stream, or a restart from older
  durable state) cannot be resumed by deltas — the uplink re-anchors from
  a snapshot of its partition, flagged ``reset``;
* a shard whose GENERATION the aggregator doesn't recognize (first
  contact, or the aggregator met a previous incarnation) cannot replay
  history its store never captured — same re-sync: the partition encodes
  as one snapshot record flagged ``reset``, which makes the aggregator
  drop the shard's old rows before applying (bit-exact: the snapshot IS
  the sum of every window the shard folded).

Failure domain: the whole shard. A failed fetch aborts the tick (nothing
folds, nothing ships, the window refetches next tick) — per-workload
quarantine stays a single-scanner concern; at the aggregator a silent
shard's rows keep serving with ``stale_since`` marks.

``krr-tpu shard`` (:func:`run_shard`) runs one as a process; tests and
``bench.py`` drive ticks in-process with a pinned clock.
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import random
import time
from collections import deque
from typing import Callable, Optional

import numpy as np

from krr_tpu.core.config import Config
from krr_tpu.core.durastore import encode_ops
from krr_tpu.core.runner import ScanSession
from krr_tpu.core.streaming import DigestStore, object_key
from krr_tpu.federation.protocol import (
    FED_MAGIC,
    FRAME_OVERHEAD,
    MSG_ACK,
    MSG_DELTA,
    MSG_HELLO,
    MSG_INVENTORY,
    MSG_WELCOME,
    PROTOCOL_VERSION,
    ProtocolError,
    decode_control,
    encode_control,
    encode_inventory,
    encode_message,
    read_message,
)
from krr_tpu.federation.ring import HashRing, RingNode, parse_ring, partition_ops
from krr_tpu.obs.trace import Tracer, propagation_context
from krr_tpu.utils.logging import KrrLogger


def parse_endpoint(value: str, flag: str) -> "tuple[str, int]":
    """``host:port`` → (host, port), with IPv6 bracket support."""
    host, sep, port = value.rpartition(":")
    if not sep or not port.isdigit():
        raise ValueError(f"{flag} must be host:port, got {value!r}")
    return host.strip("[]") or "127.0.0.1", int(port)


class Uplink:
    """One KRRFED1 stream: buffered, acked, auto-reconnecting delivery of
    already-framed delta records to one aggregator endpoint.

    The shard owns the ENCODING (one record per ring node per tick) and
    each uplink owns the DELIVERY state for one endpoint: the unacked
    buffer, the acked watermark, the connection, and the reconnect
    backoff. Endpoints of the same ring node receive the same ``offer``
    calls with the same frame objects — the replicated WAL costs one set
    of record bytes regardless of standby count. The region→global tier
    reuses this class verbatim: an aggregator-backed server constructs a
    standalone Uplink and offers its own store's captured ops.

    Reconnect backoff mirrors the Prometheus retry ladder's semantics
    (``0.25·2^(n−1)`` capped pre-jitter, ±50% jitter): after an aggregator
    restart, N shards' handshakes decorrelate instead of herding. A
    successful connect — or an explicit endpoint repoint via
    :meth:`reset_backoff` — re-arms immediate attempts.
    """

    def __init__(
        self,
        *,
        stream_id: str,
        host: str,
        port: int,
        generation: str,
        hello_spec: dict,
        snapshot_fn: Callable[[], "Optional[tuple[int, bytes]]"],
        metrics,
        logger: KrrLogger,
        buffer_cap: int,
        backoff_cap: float,
        node: str = "default",
        clusters_fn: Optional[Callable[[], list]] = None,
        inventory_fn: Optional[Callable[[], "Optional[list]"]] = None,
        on_ack: Optional[Callable[[], None]] = None,
    ) -> None:
        self.stream_id = stream_id
        self.node = node
        self.host = host
        self.port = port
        self.generation = generation
        self.hello_spec = dict(hello_spec)
        self.snapshot_fn = snapshot_fn
        self.clusters_fn = clusters_fn
        self.inventory_fn = inventory_fn
        self.metrics = metrics
        self.logger = logger
        #: (epoch, framed DELTA message) awaiting this endpoint's ack.
        #: Bounded: past ``buffer_cap`` records the backlog COLLAPSES into
        #: one snapshot record — a days-long endpoint outage must cost one
        #: partition-sized record, not one delta per tick until OOM.
        self.buffer: "deque[tuple[int, bytes]]" = deque()
        self.buffer_cap = int(buffer_cap)
        self.backoff_cap = float(backoff_cap)
        self.acked = 0
        self._sent_through = 0
        self._inventory_dirty = True
        self._on_ack = on_ack
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._recv_task: Optional[asyncio.Task] = None
        self._attempts = 0
        self._next_attempt = 0.0

    # ---------------------------------------------------------------- state
    @property
    def connected(self) -> bool:
        return self._writer is not None

    def reset_backoff(self) -> None:
        """Re-arm immediate connect attempts (endpoint repointed, or the
        caller knows the aggregator just came back)."""
        self._attempts = 0
        self._next_attempt = 0.0

    def mark_inventory_dirty(self) -> None:
        self._inventory_dirty = True

    async def offer(self, epoch: int, frame: bytes) -> None:
        """Buffer one framed record for delivery (shared bytes across the
        node's endpoints — append only, no copy)."""
        self.buffer.append((epoch, frame))
        if len(self.buffer) > self.buffer_cap:
            await self._collapse()

    async def _collapse(self) -> None:
        """Replace the whole unacked backlog with ONE snapshot record at
        the current epoch. The snapshot is flagged ``reset`` (the
        aggregator drops this stream's superseded rows first), so it is
        bit-exact — the partition state IS the sum of every buffered delta
        plus the acked history — and bounded by the partition size instead
        of the outage length. The aggregator accepts reset records at any
        epoch, so the collapsed epoch sequence re-anchors cleanly."""
        dropped = len(self.buffer)
        self.buffer.clear()
        snapshot = await asyncio.to_thread(self.snapshot_fn)
        if snapshot is not None:
            self.buffer.append(snapshot)
            self._sent_through = min(self._sent_through, snapshot[0] - 1)
        self.logger.warning(
            f"[{self.stream_id}] unacked backlog to {self.host}:{self.port} hit "
            f"{dropped} records (--federation-queue-records {self.buffer_cap}) — "
            f"collapsed into one snapshot record; the aggregator re-syncs from it"
        )

    async def _resync(self) -> None:
        """Re-anchor this endpoint from a partition snapshot: buffered
        deltas are useless to it (unknown generation, or an acked epoch
        regressed behind our pruned buffer) and the reset-flagged snapshot
        reconstructs the partition exactly at the current epoch."""
        self.buffer.clear()
        self.acked = 0
        self._sent_through = 0
        snapshot = await asyncio.to_thread(self.snapshot_fn)
        if snapshot is not None:
            self.buffer.append(snapshot)
            self._sent_through = self.acked = snapshot[0] - 1

    # ------------------------------------------------------------ transport
    async def _connect(self) -> None:
        if self._recv_task is not None and not self._recv_task.done():
            self._recv_task.cancel()
        reader, writer = await asyncio.open_connection(self.host, self.port)
        try:
            writer.write(
                FED_MAGIC
                + encode_control(
                    MSG_HELLO,
                    shard_id=self.stream_id,
                    generation=self.generation,
                    version=PROTOCOL_VERSION,
                    spec=self.hello_spec,
                    clusters=self.clusters_fn() if self.clusters_fn else [],
                )
            )
            await writer.drain()
            message = await read_message(reader)
            if message is None or message[0] != MSG_WELCOME:
                raise ProtocolError("aggregator closed the handshake without WELCOME")
            welcome = decode_control(message[1])
            if "error" in welcome:
                raise ProtocolError(
                    f"aggregator refused the handshake: {welcome['error']}"
                )
        except BaseException:
            writer.close()
            raise
        self._inventory_dirty = True
        if welcome.get("generation") != self.generation:
            # The aggregator never met THIS store: nothing it acked maps to
            # our epochs. Re-sync from state — drop the buffered deltas
            # (the snapshot subsumes them) and ship the partition as one
            # reset record.
            await self._resync()
            self.logger.info(
                f"[{self.stream_id}] aggregator at {self.host}:{self.port} does "
                f"not know generation {self.generation} — re-syncing from a snapshot"
            )
        else:
            acked = int(welcome.get("acked_epoch", 0))
            if acked < self.acked:
                # The endpoint REGRESSED (standby takeover, or a restart
                # from older durable state): epochs in (acked, self.acked]
                # are pruned from our buffer, so the next buffered delta
                # would be a gap. The snapshot re-anchors it losslessly.
                await self._resync()
                self.logger.info(
                    f"[{self.stream_id}] aggregator at {self.host}:{self.port} "
                    f"acked epoch {acked} behind our pruned buffer ({self.acked}) "
                    f"— re-syncing from a snapshot"
                )
            else:
                self.acked = max(self.acked, acked)
                self._prune_acked()
                # Re-send everything past the ack (the torn-stream heal):
                # the aggregator discards any duplicate it already enqueued.
                self._sent_through = self.acked
        self._reader, self._writer = reader, writer
        self._recv_task = asyncio.ensure_future(self._recv_loop(reader))
        self.metrics.inc("krr_tpu_federation_reconnects_total")

    def _prune_acked(self) -> None:
        while self.buffer and self.buffer[0][0] <= self.acked:
            self.buffer.popleft()

    async def _recv_loop(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                message = await read_message(reader)
                if message is None:
                    break
                kind, body = message
                if kind == MSG_ACK:
                    ack = decode_control(body)
                    self.acked = max(self.acked, int(ack.get("epoch", 0)))
                    self._prune_acked()
                    if self._on_ack is not None:
                        self._on_ack()
        except (ProtocolError, OSError):
            pass  # the connection is dead; the next pump reconnects
        finally:
            # CancelledError propagates (close() owns the suppression —
            # swallowing it here would make the task complete "normally"
            # and break outer cancellation scopes). Only tear down OUR
            # connection: a reconnect may already have installed a fresh
            # reader/writer by the time this loop unwinds.
            if self._reader is reader:
                self._disconnect()

    def _disconnect(self) -> None:
        writer, self._reader, self._writer = self._writer, None, None
        if writer is not None:
            writer.close()

    async def pump(self) -> None:
        """Send whatever is due: (re)connect when the backoff window
        allows, the current inventory when it changed, then every buffered
        record past ``_sent_through``. Send failures just mark the stream
        down — the next pump retries."""
        if self._writer is None:
            if time.monotonic() < self._next_attempt:
                return
            try:
                await self._connect()
            except (OSError, ProtocolError, asyncio.IncompleteReadError) as e:
                self._attempts += 1
                # PR 7's retry semantics (`prometheus.py::_retrying`): cap
                # pre-jitter so deep ladders stay bounded, ±50% jitter so a
                # fleet of shards reconnecting to a restarted aggregator
                # decorrelates instead of re-herding every cycle.
                wait = min(
                    0.25 * 2 ** (self._attempts - 1), self.backoff_cap
                ) * random.uniform(0.5, 1.5)
                self._next_attempt = time.monotonic() + wait
                self.metrics.inc("krr_tpu_federation_uplink_retries_total")
                self.logger.warning(
                    f"[{self.stream_id}] cannot reach aggregator at "
                    f"{self.host}:{self.port}: {e} — buffering "
                    f"({len(self.buffer)} unacked record(s)), retrying in {wait:.2f}s"
                )
                return
            self._attempts = 0
        writer = self._writer
        try:
            if self._inventory_dirty and self.inventory_fn is not None:
                objects = self.inventory_fn()
                if objects is not None:
                    # Serialized off the loop (a fleet-scale inventory is
                    # tens of MB of model_dump + JSON — the aggregator
                    # offloads the same-size decode for the same reason).
                    body = await asyncio.to_thread(encode_inventory, objects)
                    if writer is not self._writer:
                        return  # connection turned over under the encode
                    writer.write(encode_message(MSG_INVENTORY, body))
                    self._inventory_dirty = False
            for epoch, frame in list(self.buffer):
                if epoch <= self._sent_through:
                    continue
                writer.write(frame)
                self._sent_through = epoch
                self.metrics.inc(
                    "krr_tpu_federation_sent_bytes_total", len(frame) - FRAME_OVERHEAD
                )
            await writer.drain()
        except (OSError, ConnectionError):
            self.logger.warning(
                f"[{self.stream_id}] connection to {self.host}:{self.port} dropped "
                f"mid-send — re-sending from epoch {self.acked} on reconnect"
            )
            self._disconnect()

    async def wait_acked(self, epoch: int, timeout: float = 30.0) -> bool:
        """Block until this endpoint acked ``epoch``, pumping while waiting
        so a downed connection heals (standalone users — the region tier)."""
        deadline = time.monotonic() + timeout
        while self.acked < epoch:
            if time.monotonic() >= deadline:
                return False
            await self.pump()
            await asyncio.sleep(0.05)
        return True

    def status(self, epoch: int) -> dict:
        """This endpoint's posture for the shard's /healthz ``aggregators``
        block: who it streams to and how far behind the shard's current
        epoch its acks run."""
        return {
            "node": self.node,
            "endpoint": f"{self.host}:{self.port}",
            "connected": self.connected,
            "acked_epoch": self.acked,
            "epoch_lag": max(0, int(epoch) - int(self.acked)),
            "unacked_records": len(self.buffer),
        }

    async def close(self) -> None:
        if self._recv_task is not None:
            self._recv_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._recv_task
            self._recv_task = None
        self._disconnect()


class FederatedShard:
    """One scanner shard: local scan state + delta stream uplink(s)."""

    def __init__(
        self,
        config: Config,
        *,
        session: Optional[ScanSession] = None,
        shard_id: Optional[str] = None,
        clock=time.time,
        logger: Optional[KrrLogger] = None,
    ) -> None:
        self.config = config
        self.session = session or ScanSession(config, logger=logger)
        self.logger = logger or self.session.logger
        self.clock = clock
        settings = self.session.strategy.settings
        if not hasattr(settings, "cpu_spec"):
            raise ValueError(
                "krr-tpu shard requires a digest-backed strategy (tdigest): "
                "the delta stream is digest mergeability on the wire"
            )
        self.spec = settings.cpu_spec()
        self.store = DigestStore(spec=self.spec)
        self.store.track_deltas = True
        # Records land in an aggregator's MERGED store (other shards' rows
        # interleave): whole-store folds must carry their key lists — and
        # ring partitioning needs every op's keys to split it.
        self.store.capture_full_keys = True
        if not (shard_id or config.federation_shard_id):
            clusters = config.clusters if isinstance(config.clusters, list) else None
            shard_id = "/".join(clusters) if clusters else "default"
        self.shard_id = shard_id or config.federation_shard_id
        #: Fresh per store lifetime: a restarted shard can't re-send ticks
        #: its in-memory store never captured, so no aggregator may resume
        #: its old epoch watermark against us.
        self.generation = os.urandom(8).hex()
        ring_spec = getattr(config, "federation_ring", None)
        if ring_spec:
            self.nodes = parse_ring(ring_spec)
            #: key → aggregator-name assignment; None in single-aggregator
            #: mode (no partition pass on the tick path).
            self.ring: Optional[HashRing] = HashRing(self.nodes)
        elif config.federation_aggregator:
            host, port = parse_endpoint(config.federation_aggregator, "--aggregator")
            self.nodes = [RingNode(name="default", endpoints=((host, port),))]
            self.ring = None
        else:
            raise ValueError(
                "shard needs --aggregator (federation_aggregator) host:port "
                "or --federation-ring name=host:port[,name=...]"
            )
        self.scan_interval = float(config.scan_interval_seconds)
        self.discovery_interval = float(config.discovery_interval_seconds)
        self.metrics = self.session.metrics
        # Shards always record spans (the ring is bounded): the tick's scan
        # span is the ROOT the aggregator's apply and the replica's install
        # join as remote children, so without it no cross-process trace
        # stitches. The node identity stamps every exported event.
        if self.session.tracer.enabled:
            self.session.tracer.node = self.shard_id
        else:
            self.session.tracer = Tracer(
                ring_scans=getattr(config, "trace_ring_scans", 16), node=self.shard_id
            )
        self.tracer = self.session.tracer
        #: Freshness lineage stamping (metadata-only; the bench's overhead
        #: control turns it off).
        self.lineage_enabled = bool(getattr(config, "federation_lineage_enabled", True))

        self.epoch = 0
        self.last_end: Optional[float] = None
        self._objects = None
        self._discovered_at = -float("inf")
        #: Watch-driven discovery (`--discovery-mode watch`): shards ride
        #: the SAME resident inventory source as the serve scheduler — the
        #: reconcile runs every tick, and churn compaction / inventory
        #: re-sends are gated on the inventory generation so a quiet
        #: fleet's ticks stream no redundant inventory records.
        self.discovery_mode = str(getattr(config, "discovery_mode", "relist"))
        self._inventory_generation = None
        self.buffer_cap = int(getattr(config, "federation_queue_records", 4096))
        self.backoff_cap = float(
            getattr(config, "federation_backoff_cap_seconds", 5.0) or 5.0
        )
        #: Set until the first record is encoded: a fresh shard incarnation
        #: whose aggregators may hold a previous incarnation's rows flags
        #: record 1 ``reset`` so they drop those rows before applying.
        self._needs_reset = True
        #: The newest tick's observability metadata, re-stamped onto
        #: snapshot records: a resync/collapse REPLACES buffered tick
        #: records (on a real first contact the handshake routinely lands
        #: after tick 1 encoded, so the generation mismatch re-syncs and
        #: the snapshot is the only record the aggregator ever sees), and
        #: without these the fleet would lose its lineage chain and the
        #: apply span's remote link to the scan that folded the state.
        self._last_scan_ctx: "Optional[dict]" = None
        self._last_lineage: "Optional[dict]" = None
        self._ack_event = asyncio.Event()
        hello_spec = {
            "gamma": self.spec.gamma,
            "min_value": self.spec.min_value,
            "num_buckets": self.spec.num_buckets,
        }
        #: Delivery streams: one per (ring node × endpoint). In
        #: single-aggregator mode this is exactly one uplink; ring
        #: endpoints of one node share record bytes and differ only in
        #: delivery state. Stream ids are suffixed per node in ring mode so
        #: two nodes' streams never collide at a shared endpoint.
        self._uplinks: "list[Uplink]" = []
        self._node_uplinks: "dict[str, list[Uplink]]" = {}
        for node in self.nodes:
            stream_id = (
                self.shard_id if self.ring is None else f"{self.shard_id}/{node.name}"
            )
            per_node: "list[Uplink]" = []
            for host, port in node.endpoints:
                uplink = Uplink(
                    stream_id=stream_id,
                    node=node.name,
                    host=host,
                    port=port,
                    generation=self.generation,
                    hello_spec=hello_spec,
                    snapshot_fn=(
                        self._snapshot_record
                        if self.ring is None
                        else (lambda name=node.name: self._snapshot_record_for(name))
                    ),
                    clusters_fn=self._hello_clusters,
                    inventory_fn=(lambda name=node.name: self._inventory_for(name)),
                    metrics=self.metrics,
                    logger=self.logger,
                    buffer_cap=self.buffer_cap,
                    backoff_cap=self.backoff_cap,
                    on_ack=self._note_ack,
                )
                per_node.append(uplink)
                self._uplinks.append(uplink)
            self._node_uplinks[node.name] = per_node
        self.consecutive_failures = 0
        self.last_error: Optional[str] = None

    # ---------------------------------------------------- legacy delegation
    # Single-aggregator callers (tests, bench) address the shard's one
    # stream directly: host/port repoints, buffer length asserts, acked
    # reads. They delegate to the uplinks so the attributes keep meaning
    # what they meant before the ring existed.
    @property
    def host(self) -> str:
        return self._uplinks[0].host

    @host.setter
    def host(self, value: str) -> None:
        for uplink in self._uplinks:
            uplink.host = value
            uplink.reset_backoff()

    @property
    def port(self) -> int:
        return self._uplinks[0].port

    @port.setter
    def port(self, value: int) -> None:
        for uplink in self._uplinks:
            uplink.port = int(value)
            uplink.reset_backoff()

    @property
    def acked(self) -> int:
        """The fleet-safe watermark: the SLOWEST endpoint's acked epoch
        (every aggregator holds everything at or below it)."""
        return min(uplink.acked for uplink in self._uplinks)

    @acked.setter
    def acked(self, value: int) -> None:
        for uplink in self._uplinks:
            uplink.acked = int(value)

    @property
    def _buffer(self) -> "deque[tuple[int, bytes]]":
        if len(self._uplinks) == 1:
            return self._uplinks[0].buffer
        raise AttributeError(
            "per-uplink buffers in ring mode — use shard._uplinks[i].buffer"
        )

    @property
    def connected(self) -> bool:
        return all(uplink.connected for uplink in self._uplinks)

    @property
    def unacked_records(self) -> int:
        return sum(len(uplink.buffer) for uplink in self._uplinks)

    def _disconnect(self) -> None:
        """Drop every uplink's connection (tests simulate a mid-stream
        death; the next pump reconnects and re-sends past the acks)."""
        for uplink in self._uplinks:
            uplink._disconnect()

    def _note_ack(self) -> None:
        self.metrics.set("krr_tpu_federation_unacked_records", self.unacked_records)
        self._ack_event.set()

    def _hello_clusters(self) -> list:
        return sorted({obj.cluster or "" for obj in (self._objects or [])}) or (
            self.config.clusters if isinstance(self.config.clusters, list) else []
        )

    def _inventory_for(self, name: str) -> "Optional[list]":
        """The inventory one ring node receives: only the objects whose
        keys it owns (an aggregator renders exactly its partition — full
        inventories would grow empty rows for unowned keys there)."""
        if self._objects is None:
            return None
        if self.ring is None:
            return self._objects
        return [
            obj for obj in self._objects if self.ring.owner(object_key(obj)) == name
        ]

    # ------------------------------------------------------------- scanning
    def _step_seconds(self) -> float:
        from krr_tpu.integrations.prometheus import effective_step_seconds

        return float(
            effective_step_seconds(
                self.session.strategy.settings.timeframe_timedelta.total_seconds()
            )
        )

    async def _discover(self, now: float) -> None:
        objects = await self.session.discover()
        if not objects and self.store.keys:
            # Fail-soft like the scheduler: an empty inventory over a
            # non-empty store is overwhelmingly an apiserver outage, and
            # compacting on it would stream fleet-wide drop ops to the
            # aggregator — destroying accumulated history centrally too.
            self.metrics.inc("krr_tpu_discovery_failures_total")
            self.logger.warning(
                f"[shard {self.shard_id}] discovery returned no objects while the "
                f"local store holds {len(self.store.keys)} rows — keeping the "
                f"previous inventory"
            )
            return
        self._objects = objects
        self._discovered_at = now
        self.metrics.set("krr_tpu_fleet_objects", len(objects))
        # Compaction and the inventory re-send are gated on the inventory
        # generation when the source exposes one (watch mode, where
        # discovery runs every tick): only actual churn pays the store
        # compaction or streams a fresh inventory record. Relist sources
        # (generation None) keep today's per-discovery behavior.
        generation_fn = getattr(
            self.session.get_inventory(), "inventory_generation", None
        )
        generation = generation_fn() if callable(generation_fn) else None
        if generation is not None and generation == self._inventory_generation:
            return
        # Churn compaction: the captured drop ops ride the next delta
        # record, so deleted workloads leave the aggregators' stores too.
        dropped = self.store.compact({object_key(obj) for obj in objects})
        if dropped:
            self.metrics.inc("krr_tpu_store_compacted_rows_total", dropped)
        self._inventory_generation = generation
        for uplink in self._uplinks:
            uplink.mark_inventory_dirty()

    async def tick(self, now: Optional[float] = None) -> bool:
        """One scan tick: (maybe) re-discover, fetch the due window, fold,
        encode the captured deltas as one record per aggregator, buffer +
        send them. Returns False when no new window was due (the pump
        still runs, so a downed connection keeps retrying between due
        windows).

        The whole tick runs under a root ``scan`` span whose propagation
        context rides the tick's delta records — the aggregator's
        ``apply_record`` span and (transitively) the replica's ``install``
        span join it as remote children, so one stitched trace covers the
        epoch's full shard→aggregator→replica journey."""
        if now is None:
            now = float(self.clock())
        with self.tracer.span("scan", kind="shard", shard=self.shard_id) as scan_span:
            did_scan = await self._tick_traced(scan_span, now)
            if not did_scan:
                scan_span.set(kind="skipped")
        if not did_scan:
            self.tracer.discard(scan_span.trace_id)
        return did_scan

    async def _tick_traced(self, scan_span, now: float) -> bool:
        settings = self.session.strategy.settings
        step = self._step_seconds()
        self.session.begin_scan()

        if (
            self._objects is None
            or now - self._discovered_at >= self.discovery_interval
            or self.discovery_mode == "watch"
        ):
            await self._discover(now)
        objects = self._objects or []

        if self.last_end is None:
            start = now - settings.history_timedelta.total_seconds()
            if getattr(self.config, "fetch_downsample", "off") != "off":
                # Same grid alignment as the serve scheduler: downsampling
                # is only exact on the absolute step grid.
                start -= start % step
            kind = "full"
        else:
            start = self.last_end + step
            kind = "delta"
            if start > now:
                self.metrics.inc("krr_tpu_scans_skipped_total")
                await self._pump()
                return False
        end = start + ((now - start) // step) * step

        # Leg split, mirroring the scheduler: workloads that appeared since
        # the last tick get a full-window backfill beside the fleet delta
        # (a delta-width fetch would lose their pre-discovery history).
        backfill_start = end - (settings.history_timedelta.total_seconds() // step) * step
        fresh = []
        seasoned = []
        if kind == "delta":
            for obj in objects:
                (fresh if object_key(obj) not in self.store else seasoned).append(obj)
        else:
            seasoned = objects

        legs = []
        if seasoned or not fresh:
            legs.append((seasoned, start, kind))
        if fresh:
            legs.append((fresh, backfill_start, "backfill"))
        step_seconds = settings.timeframe_timedelta.total_seconds()
        # Whole-shard failure domain: raise_on_failure aborts the tick on
        # any terminal fetch failure — nothing folds, nothing ships, the
        # window refetches next tick, and the AGGREGATOR's staleness marks
        # cover the serving side.
        fleets = await asyncio.gather(
            *[
                self.session.gather_fleet_digests(
                    leg_objects,
                    history_seconds=end - w_start,
                    step_seconds=step_seconds,
                    end_time=end,
                    raise_on_failure=True,
                )
                for leg_objects, w_start, _ in legs
                if leg_objects
            ],
            return_exceptions=True,
        )
        for fleet in fleets:
            if isinstance(fleet, BaseException):
                raise fleet

        from krr_tpu.strategies.simple import MEMORY_SCALE

        for fleet in fleets:
            self.store.fold_fleet(fleet, MEMORY_SCALE)
        self.last_end = end

        extra = {"window_end": end, "window_start": start, "kind": kind}
        ctx = propagation_context(scan_span, node=self.shard_id)
        if ctx is not None:
            extra["trace"] = ctx
        self._last_scan_ctx = ctx
        if self.lineage_enabled:
            # Lineage stage 1: the tick's newest sample is the window end;
            # the fold finished "now" by THIS process's clock. Metadata
            # only — the record's ops and the stores they build are
            # bit-identical with lineage off.
            extra["lineage"] = {
                "shard": self.shard_id,
                "newest_sample_ts": float(end),
                "fold_ts": float(now),
            }
            self._last_lineage = extra["lineage"]
        await self._encode_tick(extra=extra)
        scan_span.set(
            window_start=round(start, 3),
            window_end=round(end, 3),
            objects=len(objects),
            epoch=self.epoch,
        )
        self.metrics.inc("krr_tpu_scans_total", kind="shard")
        self.metrics.set("krr_tpu_scan_window_seconds", end - start)
        self.metrics.set("krr_tpu_last_scan_timestamp_seconds", end)
        self.metrics.set("krr_tpu_digest_store_rows", len(self.store.keys))
        if fresh:
            self.metrics.inc("krr_tpu_backfilled_objects_total", len(fresh))
        await self._pump()
        return True

    async def _encode_tick(self, *, extra: dict) -> None:
        """Capture → partition → record per aggregator → buffer: one epoch
        per tick, shared by every node's record (and every endpoint's
        delivery), so ``wait_acked(self.epoch)`` means "the whole tick
        landed everywhere". The partition split and CSR encodes run off the
        loop (fleet-scale records are real numpy + zip work that would
        stall ack processing). Nodes with no ops this tick still get an
        empty record — it carries the window metadata their staleness
        accounting rides on, and keeps the per-node epoch sequence gapless.
        """
        ops = self.store.pending_ops()
        if self._needs_reset:
            extra = {**extra, "reset": True}
            self._needs_reset = False
        if self.ring is None:
            parts = {"default": ops}
        else:
            parts = await asyncio.to_thread(partition_ops, ops, self.ring.owner)
        epoch = self.epoch + 1
        for name, uplinks in self._node_uplinks.items():
            payload = await asyncio.to_thread(
                encode_ops,
                parts.get(name, []),
                epoch=epoch,
                extra=extra,
                num_buckets=self.spec.num_buckets,
            )
            frame = encode_message(MSG_DELTA, payload)
            for uplink in uplinks:
                await uplink.offer(epoch, frame)
        self.epoch = epoch
        self.store.clear_pending(len(ops))
        if self.ring is not None:
            spread = self.ring.spread(self.store.keys)
            self.metrics.set("krr_tpu_federation_ring_nodes", len(spread))
            for name, count in spread.items():
                self.metrics.set("krr_tpu_federation_ring_keys", count, node=name)
        self.metrics.set("krr_tpu_federation_unacked_records", self.unacked_records)

    def _snapshot_record(self) -> "Optional[tuple[int, bytes]]":
        """The whole store as ONE reset record at the current epoch — the
        single-aggregator resync path."""
        return self._snapshot_record_for(None)

    def _snapshot_record_for(self, owner: "Optional[str]") -> "Optional[tuple[int, bytes]]":
        """One ring node's partition (or the whole store for ``None``) as
        ONE reset record at the current epoch — the resync path. Applying
        it to fresh aggregator rows reconstructs the partition exactly (the
        store IS the sum of its folded windows). An EMPTY partition at a
        live epoch still yields a record: its reset drops whatever stale
        rows the endpoint holds for this stream, and it re-anchors the
        epoch sequence. Only at epoch 0 (nothing ever encoded — record 1's
        ``reset`` flag covers first contact) is there nothing to say."""
        store = self.store
        if owner is None or self.ring is None:
            keys = list(store.keys)
            arrays = (
                store.cpu_counts,
                store.cpu_total,
                store.cpu_peak,
                store.mem_total,
                store.mem_peak,
            )
        else:
            rows = [
                i for i, key in enumerate(store.keys) if self.ring.owner(key) == owner
            ]
            idx = np.asarray(rows, dtype=np.int64)
            keys = [store.keys[i] for i in rows]
            arrays = (
                store.cpu_counts[idx],
                store.cpu_total[idx],
                store.cpu_peak[idx],
                store.mem_total[idx],
                store.mem_peak[idx],
            )
        ops = [("fold", keys, *arrays)] if keys else []
        if not ops and self.epoch <= 0:
            return None
        extra: dict = {"reset": True, "window_end": self.last_end, "kind": "snapshot"}
        # The snapshot IS the last tick's folded state, so it carries that
        # tick's trace context and lineage fragment: the aggregator's
        # apply span still joins the scan that produced the data, and the
        # freshness chain reports the fold's real age, not the resync's.
        if self._last_scan_ctx is not None:
            extra["trace"] = dict(self._last_scan_ctx)
        if self.lineage_enabled and self._last_lineage is not None:
            extra["lineage"] = dict(self._last_lineage)
        payload = encode_ops(
            ops,
            epoch=self.epoch,
            extra=extra,
            num_buckets=self.spec.num_buckets,
        )
        return self.epoch, encode_message(MSG_DELTA, payload)

    async def run_once(self, now: Optional[float] = None) -> "Optional[bool]":
        """One guarded tick (the shard loop's unit): failures count and
        degrade — the stream pump still runs so the uplinks heal while the
        backend is down."""
        try:
            did_scan = await self.tick(now)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self.metrics.inc("krr_tpu_scan_failures_total")
            self.consecutive_failures += 1
            self.last_error = f"{type(e).__name__}: {e}"[:300]
            self.logger.warning(
                f"[shard {self.shard_id}] scan failed: {e} — the window refetches next tick"
            )
            self.logger.debug_exception()
            with contextlib.suppress(Exception):
                await self._pump()
            return None
        else:
            self.consecutive_failures = 0
            return did_scan

    # ------------------------------------------------------------- transport
    async def _pump(self) -> None:
        for uplink in self._uplinks:
            await uplink.pump()

    async def wait_acked(self, epoch: int, timeout: float = 30.0) -> bool:
        """Block until EVERY endpoint has acked ``epoch`` (tests, graceful
        shutdown). Pumps while waiting so downed connections heal."""
        deadline = time.monotonic() + timeout
        while self.acked < epoch:
            if time.monotonic() >= deadline:
                return False
            await self._pump()
            self._ack_event.clear()
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(self._ack_event.wait(), timeout=0.1)
        return True

    def status(self) -> dict:
        """The shard's /healthz body: scan posture plus a per-aggregator
        delivery block (which node/endpoint each stream feeds and its
        acked-vs-current epoch lag), so ring placement is debuggable from
        the SHARD side."""
        return {
            "status": (
                "ok"
                if self.connected and self.consecutive_failures == 0
                else "degraded"
            ),
            "shard_id": self.shard_id,
            "generation": self.generation,
            "connected": self.connected,
            "epoch": self.epoch,
            "acked_epoch": self.acked,
            "unacked_records": self.unacked_records,
            "aggregators": [uplink.status(self.epoch) for uplink in self._uplinks],
            "ring": (
                {"nodes": sorted(self._node_uplinks)} if self.ring is not None else None
            ),
            "last_window_end": self.last_end,
            "consecutive_scan_failures": self.consecutive_failures,
            "last_scan_error": self.last_error,
            "objects": len(self._objects or []),
        }

    async def close(self) -> None:
        for uplink in self._uplinks:
            await uplink.close()
        await self.session.close()


class ShardStatusServer:
    """A minimal HTTP surface for a shard process: ``GET /healthz`` (the
    shard's scan + uplink posture as JSON), ``GET /metrics`` (the shared
    registry's exposition — the shard-side ``krr_tpu_federation_*`` family
    would otherwise be write-only: `krr_tpu_federation_unacked_records` is
    the signal that a shard is silently buffering through an aggregator
    outage, and it manifests on the SHARD), and ``GET /debug/trace``
    (the tick ring as Chrome trace JSON, node-stamped — what ``analyze
    --stitch`` fetches to join this shard's lane into the fleet trace)."""

    def __init__(self, shard: FederatedShard) -> None:
        self.shard = shard
        self._server: Optional[asyncio.AbstractServer] = None
        self._connections: "set[asyncio.StreamWriter]" = set()
        from krr_tpu.obs.metrics import record_build_info

        record_build_info(self.shard.metrics)

    async def serve(self, host: str, port: int) -> None:
        self._server = await asyncio.start_server(self._handle, host, port)

    @property
    def port(self) -> int:
        assert self._server is not None, "status server not started"
        return self._server.sockets[0].getsockname()[1]

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        import json

        self._connections.add(writer)
        try:
            request_line = await reader.readline()
            while (await reader.readline()) not in (b"\r\n", b"\n", b""):
                pass  # drain headers; GET carries no body
            parts = request_line.decode("latin-1", "replace").split()
            target = parts[1] if len(parts) >= 2 else ""
            path, _, query = target.partition("?")
            if path == "/metrics":
                from krr_tpu.obs.metrics import refresh_process_metrics

                refresh_process_metrics(self.shard.metrics)
                status, content_type = 200, "text/plain; version=0.0.4; charset=utf-8"
                body = self.shard.metrics.render().encode()
            elif path == "/healthz":
                status, content_type = 200, "application/json"
                body = (json.dumps(self.shard.status()) + "\n").encode()
            elif path == "/debug/trace":
                n = None
                for part in query.split("&"):
                    key, _, value = part.partition("=")
                    if key == "n" and value.isdigit() and int(value) > 0:
                        n = int(value)
                payload = await asyncio.to_thread(self.shard.tracer.export_chrome, n)
                status, content_type = 200, "application/json"
                body = (json.dumps(payload) + "\n").encode()
            else:
                status, content_type = 404, "application/json"
                body = (
                    b'{"error": "no route (shard serves /healthz, /metrics'
                    b' and /debug/trace)"}\n'
                )
            reason = {200: "OK", 404: "Not Found"}[status]
            writer.write(
                (
                    f"HTTP/1.1 {status} {reason}\r\n"
                    f"Content-Type: {content_type}\r\n"
                    f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
                ).encode("latin-1")
                + body
            )
            await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError, OSError):
            pass
        finally:
            self._connections.discard(writer)
            writer.close()

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            for writer in list(self._connections):
                writer.close()
            await self._server.wait_closed()
            self._server = None


async def run_shard(config: Config, *, logger: Optional[KrrLogger] = None) -> None:
    """The ``krr-tpu shard`` entry point: scan + stream until SIGINT/SIGTERM."""
    import signal

    shard = FederatedShard(config, logger=logger)
    status_server = ShardStatusServer(shard)
    await status_server.serve(config.server_host, config.server_port)
    targets = ", ".join(
        f"{uplink.stream_id}→{uplink.host}:{uplink.port}"
        for uplink in shard._uplinks
    )
    shard.logger.info(
        f"Shard {shard.shard_id} scanning every {shard.scan_interval:.0f}s, "
        f"streaming deltas to {targets}; status on "
        f"http://{config.server_host}:{status_server.port} (/healthz, /metrics)"
    )
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # non-unix event loops
            pass
    # kill -USR2 <pid> dumps the tick trace ring + a metrics snapshot to
    # timestamped files without stopping the shard — the same escape hatch
    # serve has (`krr_tpu.obs.dump`).
    from krr_tpu.obs.dump import install_signal_dump

    install_signal_dump(
        shard.tracer,
        shard.metrics,
        trace_target=config.trace_path,
        metrics_target=config.metrics_dump_path,
        logger=shard.logger,
        loop=loop,
    )
    try:
        while not stop.is_set():
            await shard.run_once()
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(stop.wait(), timeout=shard.scan_interval)
    finally:
        shard.logger.info("Shard shutting down")
        # Best-effort drain: give in-flight records a moment to ack so a
        # rolling restart doesn't force a re-send of the whole tail.
        if shard.epoch > shard.acked:
            with contextlib.suppress(Exception):
                await shard.wait_acked(shard.epoch, timeout=5.0)
        await status_server.close()
        await shard.close()
        if config.trace_path:
            from krr_tpu.obs.trace import write_chrome_trace

            write_chrome_trace(shard.tracer, config.trace_path)
        if config.profile_path:
            from krr_tpu.obs.profile import write_profile_report

            write_profile_report(shard.tracer, config.profile_path)
