"""The remote-write HTTP listener.

A minimal asyncio HTTP/1.1 server for exactly one verb: ``POST
/api/v1/write`` with a snappy-framed protobuf body (the server app's
``HttpApp`` is GET/HEAD-only by design, so the write path gets its own
socket and port — also the deployment shape Prometheus expects).

Protocol posture: bodies require a ``Content-Length`` (chunked uploads get
411 — remote-write senders always set it), oversized declarations are
refused with 413 BEFORE reading the body, malformed frames are 400, and
every accepted body answers 204 on a kept-alive connection. A failing
request never takes the listener down: the catch-all 500 arm keeps serving.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from krr_tpu.ingest.plane import IngestPlane
from krr_tpu.integrations.native import RemoteWriteError, RemoteWriteTooLarge

_MAX_HEADER_BYTES = 16384

_REASONS = {
    204: "No Content",
    400: "Bad Request",
    404: "Not Found",
    405: "Method Not Allowed",
    411: "Length Required",
    413: "Payload Too Large",
    500: "Internal Server Error",
}


class RemoteWriteListener:
    def __init__(
        self,
        plane: IngestPlane,
        *,
        host: str = "0.0.0.0",
        port: int = 0,
        max_body_bytes: int = 16 << 20,
        metrics=None,
        logger=None,
    ) -> None:
        self.plane = plane
        self.host = host
        self.port = port  # 0 until started; then the bound port
        self.max_body_bytes = int(max_body_bytes)
        self.metrics = metrics
        self.logger = logger
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._serve_connection, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    def _count(self, code: int) -> None:
        if self.metrics is not None:
            self.metrics.inc("krr_tpu_ingest_requests_total", code=str(code))

    async def _serve_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            while True:
                try:
                    head = await reader.readuntil(b"\r\n\r\n")
                except asyncio.IncompleteReadError:
                    return  # clean close between requests
                except asyncio.LimitOverrunError:
                    return
                if len(head) > _MAX_HEADER_BYTES:
                    return
                keep_alive = await self._serve_request(head, reader, writer)
                if not keep_alive:
                    return
        except (ConnectionError, asyncio.CancelledError):
            return
        except Exception:  # a torn connection must never kill the listener
            if self.logger is not None:
                self.logger.exception("ingest listener connection error")
            return
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass

    async def _serve_request(self, head: bytes, reader, writer) -> bool:
        lines = head.split(b"\r\n")
        parts = lines[0].split()
        if len(parts) != 3:
            await self._respond(writer, 400, close=True)
            return False
        method, path = parts[0].decode("latin-1"), parts[1].decode("latin-1")
        headers: dict[str, str] = {}
        for line in lines[1:]:
            if b":" in line:
                name, _, value = line.partition(b":")
                headers[name.decode("latin-1").strip().lower()] = value.decode("latin-1").strip()
        close_requested = headers.get("connection", "").lower() == "close"

        if method != "POST":
            await self._respond(writer, 405, close=close_requested)
            return not close_requested
        if path.split("?", 1)[0] != "/api/v1/write":
            await self._drain(reader, headers)
            await self._respond(writer, 404, close=close_requested)
            return not close_requested
        length_header = headers.get("content-length")
        if length_header is None or not length_header.isdigit():
            # Chunked/absent lengths: refuse rather than stream-parse —
            # remote-write senders always declare the body size.
            await self._respond(writer, 411, close=True)
            return False
        length = int(length_header)
        if length > self.max_body_bytes:
            self._count(413)
            await self._respond(writer, 413, close=True)
            return False
        try:
            body = await reader.readexactly(length)
        except asyncio.IncompleteReadError:
            return False

        try:
            accepted = self.plane.ingest_body(body)
        except RemoteWriteTooLarge:
            self._count(413)
            await self._respond(writer, 413, close=close_requested)
            return not close_requested
        except RemoteWriteError:
            self._count(400)
            await self._respond(writer, 400, close=close_requested)
            return not close_requested
        except Exception:
            if self.logger is not None:
                self.logger.exception("ingest body failed")
            self._count(500)
            await self._respond(writer, 500, close=close_requested)
            return not close_requested
        self._count(204)
        if self.metrics is not None:
            self.metrics.inc("krr_tpu_ingest_bytes_total", float(len(body)))
            if accepted:
                self.metrics.inc("krr_tpu_ingest_samples_total", float(accepted))
        await self._respond(writer, 204, close=close_requested)
        return not close_requested

    async def _drain(self, reader, headers: dict) -> None:
        length_header = headers.get("content-length", "")
        if length_header.isdigit():
            length = int(length_header)
            if 0 < length <= self.max_body_bytes:
                try:
                    await reader.readexactly(length)
                except asyncio.IncompleteReadError:
                    pass

    async def _respond(self, writer, code: int, close: bool = False) -> None:
        connection = "close" if close else "keep-alive"
        writer.write(
            (
                f"HTTP/1.1 {code} {_REASONS[code]}\r\n"
                f"Content-Length: 0\r\nConnection: {connection}\r\n\r\n"
            ).encode("latin-1")
        )
        await writer.drain()
