"""The background scan scheduler: incremental delta folds + slow re-discovery.

Tick semantics (the amortization contract):

* The FIRST scan fetches the strategy's full history window
  ``[now - history, now]`` and folds it into the resident digest store.
* Every later tick fetches only the DELTA window ``[last_end + step, now]``
  — the samples Prometheus's evaluation grid adds after the last folded
  window — and folds it in. Digest bucket counts are integer-valued and
  merge by exact addition (peaks by max), so the accumulated store is
  bit-identical to a cold scan over the union window; nothing is ever
  re-fetched or double-counted.
* Discovery (apiserver inventory) runs on its own slower cadence; a
  re-discovery compacts the store to the currently-discovered fleet so
  workload churn can't grow it without bound.

A scan runs entirely OUTSIDE the state's read/write lock — fetch and fold
build a private window, the recommendation compute reads the store from a
worker thread — and publishes with one atomic snapshot swap at the end, so
queries serve the previous result throughout. ``state.last_end`` advances
only after a fold completes: a scan cancelled mid-fetch (shutdown, restart)
simply refetches its window on the next tick.

Failure domains (fault-isolated degraded ticks): a workload whose fetch
fails TERMINALLY this tick is QUARANTINED — its rows stay unfolded (the
one-shot CLI's degrade-to-UNKNOWN would here fold an empty window and
advance past it, silently losing those samples from the accumulated store),
its last-good digests keep serving with a ``stale_since`` mark, and on a
later tick a CATCH-UP leg refetches the union of every window it missed
from its own cursor — digest mergeability makes the recovered store
bit-identical to one that never missed a window. The quarantine cursor
persists in the store's extra_meta (same atomic save as the window cursor),
a workload stale past ``--max-staleness`` drops its row and re-enters as
fresh (full backfill), and a tick whose fetch-success fraction falls below
``--min-fetch-success-pct`` still hard-aborts — folding and publishing a
mostly-empty fleet would be worse than serving the previous result. The
whole tick also still aborts on infrastructure errors (cancellation,
discovery failures mid-flight), which leave store, cursor, and quarantine
untouched for a clean refetch.

Window edges are clamped to the Prometheus evaluation grid: a range query
evaluates at ``start, start + step, …``, so the fetched window's true right
edge is the last grid point ≤ now. ``last_end`` records THAT point — with a
wall-clock right edge, tick jitter (a 90 s sleep on a 60 s grid) would skip
the grid samples between the last evaluated point and the clock reading.

The publish leg runs through `krr_tpu.history`: every recompute's raw
recommendations append to the journal (the flight recorder behind
``GET /history`` / ``GET /drift`` / ``krr-tpu diff``), and the values that
reach the published snapshot are filtered by the hysteresis gate — they only
move when drift exceeds the dead band for the confirmation window, so the
snapshot the fleet consumes is stable by construction while the journal
retains the raw series (``--no-hysteresis`` restores verbatim publishing).
"""

from __future__ import annotations

import asyncio
import contextlib
import time
from typing import Callable, Optional

import numpy as np

from krr_tpu.core.runner import ScanSession, round_allocations
from krr_tpu.core.streaming import object_key
from krr_tpu.history.policy import HysteresisGate
from krr_tpu.models.objects import K8sObjectData
from krr_tpu.models.result import ResourceScan, Result
from krr_tpu.server.state import ServerState, Snapshot
from krr_tpu.utils.logging import KrrLogger


class ScanScheduler:
    """Drives a :class:`ScanSession` incrementally against a :class:`ServerState`."""

    def __init__(
        self,
        session: ScanSession,
        state: ServerState,
        *,
        scan_interval: float,
        discovery_interval: float,
        clock: Callable[[], float] = time.time,
        logger: Optional[KrrLogger] = None,
        durable=None,
        aggregator=None,
        ingest=None,
        uplink=None,
    ) -> None:
        self.session = session
        self.state = state
        #: Push ingest plane (`krr_tpu.ingest`, ``--metrics-mode push``):
        #: when set, delta ticks fold seasoned workloads whose buffered
        #: streams COVER the window straight from the plane — zero range
        #: queries — while anything the watermarks can't vouch for rides
        #: the classic range legs (gap backfill). None = pull mode.
        self.ingest = ingest
        #: Federation mode (`krr_tpu.federation.aggregator`): when set, the
        #: scheduler stops scanning — scanner shards own discover+fetch+fold
        #: — and each tick becomes an AGGREGATE tick instead: replay queued
        #: shard delta records into the fleet store (the WAL recovery path)
        #: and publish the merged view through the unchanged pipeline.
        self.aggregator = aggregator
        #: Tiered aggregation (`--federation-uplink`): a standalone shard
        #: Uplink (`krr_tpu.federation.shard.Uplink`) this REGION
        #: aggregator streams its own store's captured ops through, to a
        #: higher-tier (global) aggregator — the shard protocol verbatim,
        #: so the tiers compose without a second wire format. The region's
        #: store runs with delta capture on; each aggregate tick encodes
        #: the newly captured ops as one record at ``uplink_epoch + 1``.
        self.uplink = uplink
        self.uplink_epoch = 0
        #: How many of the store's queued pending ops are already encoded
        #: into uplink records (the uplink consumes the SAME capture the
        #: durable persist drains; a failed persist keeps ops queued, and
        #: this cursor keeps the uplink from re-encoding them).
        self._uplink_consumed = 0
        #: First uplink record flags ``reset`` — the global tier may hold
        #: a previous incarnation's rows for this region.
        self._uplink_needs_reset = True
        self._uplink_inventory_keys: "Optional[tuple]" = None
        #: The durable persistence engine (`krr_tpu.core.durastore`) when
        #: the serve composition opened one for state_path — per-tick delta
        #: WAL appends, threshold compaction, and the publish epoch the
        #: journal reconciles against. None (direct construction, no
        #: state_path) falls back to the legacy whole-file save.
        self.durable = durable
        self.scan_interval = float(scan_interval)
        self.discovery_interval = float(discovery_interval)
        self.clock = clock
        self.logger = logger or session.logger
        self._objects: Optional[list[K8sObjectData]] = None
        self._discovered_at: float = -float("inf")
        self._task: Optional[asyncio.Task] = None
        #: The state file (tdigest ``state_path``) the resident store syncs
        #: to after each fold, when configured — restarts resume the digests.
        #: A RUNNING server owns its state file exclusively: each tick saves
        #: the resident store over it, so a concurrent one-shot
        #: ``tdigest --state_path`` merge against the same file would be
        #: silently overwritten — run backfills before starting the server.
        self.state_path: Optional[str] = getattr(session.strategy.settings, "state_path", None)
        # Resume the window cursor alongside the digests: without it a
        # restart's first scan would fold the FULL history window into a
        # store that already contains it — double-counting every overlap
        # sample. The cursor lives in the store's OWN extra_meta (one atomic
        # save covers arrays + cursor; a sidecar could desync on a crash
        # between two writes, losing or double-counting a window).
        if self.state_path and self.state.store.keys and self.state.last_end is None:
            cursor = self.state.store.extra_meta.get("serve_last_end")
            if cursor is not None:
                self.state.last_end = float(cursor)
            else:
                self.logger.warning(
                    f"Digest state at {self.state_path} carries no serve window cursor — "
                    f"the first scan re-folds the full window on top of the resumed store"
                )
        # Degraded-tick policy (fault isolation): failed workload fetches
        # QUARANTINE — their windows stay unfolded, their last-good digests
        # carry forward with stale marks — instead of aborting the whole
        # tick, unless the fetch-success fraction falls below the floor.
        config = session.config
        self.min_fetch_success_pct = float(getattr(config, "min_fetch_success_pct", 100.0))
        #: Staleness budget: past it a quarantined workload's accumulated
        #: row drops and it re-enters as fresh (full-window backfill).
        self.max_staleness = (
            float(getattr(config, "max_staleness_seconds", 0.0)) or 10.0 * self.scan_interval
        )
        #: Last completed tick's distillables for the flight recorder
        #: (`krr_tpu.obs.timeline`): window, rows, publish verdict, persist
        #: outcome — consumed by :meth:`_observe_timeline` in run_once.
        self.last_tick_stats: "Optional[dict]" = None
        #: Cumulative fetch-plan counter totals at the last recorded tick,
        #: so the timeline record carries per-TICK coalesced/sharded/
        #: downsampled deltas instead of process-lifetime sums.
        self._plan_totals: "dict[str, float]" = {
            "coalesced": 0.0, "sharded": 0.0, "downsampled": 0.0,
        }
        #: Read-path counter totals (and /recommendations latency-histogram
        #: cumulative buckets) at the last recorded tick — the timeline
        #: record carries per-TICK served/hit/miss/shed/bytes deltas and a
        #: per-tick p99, the same delta discipline as the plan counters.
        self._read_totals: "dict[str, float]" = {}
        self._read_buckets: "Optional[dict[float, float]]" = None
        #: Watch-driven discovery (``--discovery-mode watch``): the
        #: reconcile runs EVERY tick (it is O(churn) in-memory work), and
        #: churn compaction only runs when the inventory generation moved —
        #: watch deletes feed the existing store drop ops, and a quiet
        #: fleet's ticks skip the fleet-sized masked copy entirely.
        self.discovery_mode = str(getattr(config, "discovery_mode", "relist"))
        self._compacted_generation: "Optional[int]" = None
        #: Cumulative discovery counter totals at the last recorded tick —
        #: the timeline's ``discovery`` block carries per-TICK event/relist
        #: deltas, the same delta discipline as the plan counters.
        self._discovery_totals: "dict[str, float]" = {}
        #: Push-mode divergence audit cadence (0 = auto: four scan
        #: intervals, mirroring the discovery audit's default ladder).
        self.ingest_verify_interval = (
            float(getattr(config, "ingest_verify_interval_seconds", 0.0))
            or 4.0 * self.scan_interval
        )
        self._last_ingest_verify_at: float = -float("inf")
        #: key → grid-aligned start of the first window its fetch missed:
        #: the catch-up fetch's left edge. Persisted in the store's
        #: extra_meta (same atomic save as the cursor) — a restart must
        #: refetch the missed windows, not silently skip them.
        self._quarantine: dict[str, float] = {}
        if self.state_path and self.state.store.keys and self.state.last_end is not None:
            saved = self.state.store.extra_meta.get("serve_quarantine")
            if saved:
                self._quarantine = {str(k): float(v) for k, v in saved.items()}
        # Adaptive fetch-plan telemetry rides the same atomic save: a restart
        # seeds the per-cluster planners with the previous scan's observed
        # series/bytes so the first tick's query shapes match the last one's
        # instead of re-deriving from cold routed counts.
        session.seed_fetch_plans(self.state.store.extra_meta.get("serve_fetch_plan"))
        self._publish_stale_state()
        if (
            getattr(config, "fetch_downsample", "off") != "off"
            and self.state.last_end is not None
            and float(self.state.last_end) % self._step_seconds() != 0
        ):
            # A pre-downsample deployment restored its cursor: the window
            # grid was anchored before alignment existed, every later edge
            # inherits the misalignment (realigning mid-stream would skip
            # or double-count a partial step), and eligibility will decline
            # every query — a forever-zero krr_tpu_fetch_downsampled_total.
            if getattr(config, "realign_window_grid", False) or not self.state.store.keys:
                # The one-shot --realign-window-grid escape (or a store with
                # nothing to lose): drop the cursor AND the accumulated rows
                # so the next tick runs a grid-ALIGNED full backfill — the
                # only realignment that neither skips nor double-counts a
                # partial step. The drop op rides the next durable persist.
                dropped = self.state.store.compact(frozenset())
                self.state.store.extra_meta.pop("serve_last_end", None)
                self.state.last_end = None
                self._quarantine.clear()
                self._publish_stale_state()
                self.logger.warning(
                    f"--fetch-downsample window grid realignment: dropped the "
                    f"persisted cursor and {dropped} accumulated row(s) — the "
                    f"next tick runs a grid-aligned full backfill and "
                    f"downsampling engages from it"
                )
            else:
                self.logger.warning(
                    "--fetch-downsample is on but the persisted window grid is "
                    "not aligned to the step grid (the state predates the "
                    "flag); downsampling stays disengaged until the window "
                    "grid is rebuilt — restart once with --realign-window-grid "
                    "to trade one full backfill for an aligned grid"
                )
        # The hysteresis gate on the publish path (`krr_tpu.history.policy`).
        # A resumed journal re-seeds the trailing published baselines, so a
        # restart keeps gating against the pre-restart published values
        # instead of re-publishing the whole fleet as "new".
        self.gate = HysteresisGate(
            dead_band_pct=config.hysteresis_dead_band_pct,
            confirm_ticks=config.hysteresis_confirm_ticks,
            enabled=config.hysteresis_enabled,
        )
        journal = state.journal
        if journal is not None and journal.record_count:
            published = journal.last_published()
            if published:
                keys = list(published)
                self.gate.seed(
                    keys,
                    np.asarray([published[k][0] for k in keys], np.float32),
                    np.asarray([published[k][1] for k in keys], np.float32),
                )

    # ----------------------------------------------------------- one tick
    def _step_seconds(self) -> float:
        from krr_tpu.integrations.prometheus import effective_step_seconds

        return float(
            effective_step_seconds(self.session.strategy.settings.timeframe_timedelta.total_seconds())
        )

    async def _discover(self, now: float) -> None:
        objects = await self.session.discover()
        metrics = self.state.metrics
        inventory = self.session.get_inventory()
        # Per-cluster discovery failures (fail-soft listings degraded to an
        # empty cluster): surface the FAILING CLUSTERS on /healthz instead
        # of silently scanning a smaller fleet (the loader also counts them
        # in krr_tpu_discovery_cluster_failures_total).
        failed_clusters = getattr(inventory, "last_failed_clusters", None)
        self.state.discovery_failed_clusters = dict(failed_clusters or {})
        if not objects and self.state.store.keys:
            # Discovery is fail-soft per cluster (a listing error degrades to
            # an empty list) — an empty fleet under a non-empty resident
            # store is overwhelmingly an inventory outage, not real churn,
            # and compacting on it would destroy the accumulated digest
            # history (beyond Prometheus retention, unrecoverable). Keep the
            # previous inventory and leave the discovery timestamp stale so
            # the next tick retries.
            metrics.inc("krr_tpu_discovery_failures_total")
            self.logger.warning(
                f"Discovery returned no objects while the digest store holds "
                f"{len(self.state.store.keys)} rows — keeping the previous inventory "
                f"and skipping churn compaction (transient inventory failure?)"
            )
            return
        self._objects = objects
        self._discovered_at = now
        metrics.set("krr_tpu_fleet_objects", len(objects))
        # Churn compaction: deleted workloads' rows leave the store. Done at
        # every discovery (including a state_path-resumed first one, whose
        # store may carry rows for long-gone workloads). Off the loop: at
        # fleet scale the masked copy of the [N x B] matrix is seconds of
        # numpy work that would stall every in-flight query. In watch mode
        # discovery runs EVERY tick, so the compaction is gated on the
        # inventory generation: only churn (watch deletes included) pays it.
        generation_fn = getattr(inventory, "inventory_generation", None)
        generation = generation_fn() if callable(generation_fn) else None
        if generation is not None and generation == self._compacted_generation:
            return
        dropped = await asyncio.to_thread(
            self.state.store.compact, {object_key(obj) for obj in objects}
        )
        self._compacted_generation = generation
        if dropped:
            metrics.inc("krr_tpu_store_compacted_rows_total", dropped)
            self.logger.info(f"Compacted {dropped} stale rows out of the digest store")

    def _save_store(self) -> None:
        from krr_tpu.core.streaming import DigestStore

        self.state.store.extra_meta["serve_last_end"] = self.state.last_end
        # The quarantine rides the same atomic save as the cursor: a restart
        # that resumed the cursor without it would fold plain deltas for
        # quarantined workloads and silently lose their missed windows.
        if self._quarantine:
            self.state.store.extra_meta["serve_quarantine"] = dict(self._quarantine)
        else:
            self.state.store.extra_meta.pop("serve_quarantine", None)
        # Planner telemetry persists beside the cursor so the NEXT process's
        # first scan plans from this one's observations.
        plan_states = self.session.fetch_plan_states()
        if plan_states:
            self.state.store.extra_meta["serve_fetch_plan"] = plan_states
        else:
            self.state.store.extra_meta.pop("serve_fetch_plan", None)
        if self.aggregator is not None:
            # Per-shard epoch watermarks ride the SAME record as the applied
            # ops: recovery can never see ops without the watermark that
            # acks them, which is what makes shard re-sends exactly-once
            # across aggregator restarts.
            self.state.store.extra_meta["federation"] = self.aggregator.export_meta()
        with DigestStore.locked(self.state_path):
            if self.durable is not None:
                # Sharded: one appended delta record carrying this tick's
                # folded windows + the extra_meta above (cursor, quarantine,
                # fetch plan) — the same atomicity contract as the
                # monolithic save, at a fraction of the bytes. Legacy
                # format: the classic full rewrite, unchanged on disk.
                self.durable.save_delta()
            else:
                self.state.store.save(self.state_path)

    async def _persist(self) -> None:
        """Persist the store, degrading instead of killing the tick on disk
        faults: ENOSPC/EIO leaves serve publishing from memory with
        /healthz degraded and a retry (carrying the backlog of captured
        deltas) on the next tick."""
        metrics = self.state.metrics
        try:
            await asyncio.to_thread(self._save_store)
        except OSError as e:
            metrics.inc("krr_tpu_persist_failures_total")
            self.state.persist_failures += 1
            self.state.persist_failing = True
            self.state.last_persist_error = f"{type(e).__name__}: {e}"[:300]
            # Bound the backlog: queued fold captures reference each tick's
            # DENSE window matrix — a disk that stays full must not pin one
            # per tick until the degradation it survived becomes an OOM
            # kill. Sparse re-encode is ~250x smaller and WAL-identical.
            await asyncio.to_thread(self.state.store.compact_pending)
            self.logger.warning(
                f"Persisting digest state to {self.state_path} failed ({e}) — "
                f"serving from memory; the next tick retries with the backlog"
            )
        else:
            if self.state.persist_failing:
                self.logger.info(
                    f"Digest state persistence to {self.state_path} recovered"
                )
            self.state.persist_failing = False

    # ---------------------------------------------------- tiered aggregation
    async def _uplink_tick(self, objects, window_end: float) -> None:
        """Encode this tick's newly captured store ops as one uplink record
        (epoch ``uplink_epoch + 1``) and buffer it for the global tier —
        the shard's ``_encode_tick`` with the region aggregator's merged
        store as the source. The pending-op cursor (``_uplink_consumed``)
        lets the uplink and the durable persist share one capture queue:
        under a persist failure the ops stay queued (and
        ``compact_pending`` re-encodes them in place, count preserved), so
        the cursor stays valid until the fault-free persist drains them."""
        from krr_tpu.core.durastore import encode_ops
        from krr_tpu.federation.protocol import MSG_DELTA, encode_message
        from krr_tpu.core.streaming import object_key as _object_key

        store = self.state.store
        ops = store.pending_ops()
        new = ops[self._uplink_consumed :]
        extra = {"window_end": window_end, "kind": "region"}
        if self._uplink_needs_reset:
            extra["reset"] = True
            self._uplink_needs_reset = False
        epoch = self.uplink_epoch + 1
        payload = await asyncio.to_thread(
            encode_ops,
            new,
            epoch=epoch,
            extra=extra,
            num_buckets=store.spec.num_buckets,
        )
        await self.uplink.offer(epoch, encode_message(MSG_DELTA, payload))
        self.uplink_epoch = epoch
        self._uplink_consumed = len(ops)
        if not self.state_path:
            # Memory-only region: nothing else drains the capture.
            store.clear_pending(len(ops))
            self._uplink_consumed = 0
        fingerprint = tuple(_object_key(obj) for obj in objects)
        if fingerprint != self._uplink_inventory_keys:
            self._uplink_inventory_keys = fingerprint
            self.uplink.mark_inventory_dirty()

    def _uplink_snapshot(self) -> "Optional[tuple[int, bytes]]":
        """The region's whole merged store as ONE reset record at the
        current uplink epoch — the re-sync path when the global tier never
        met this incarnation (or regressed behind the pruned buffer).
        Same contract as ``FederatedShard._snapshot_record``. Runs in a
        worker thread (Uplink calls it via ``asyncio.to_thread``)."""
        from krr_tpu.core.durastore import encode_ops
        from krr_tpu.federation.protocol import MSG_DELTA, encode_message

        store = self.state.store
        keys = list(store.keys)
        ops = (
            [
                (
                    "fold",
                    keys,
                    store.cpu_counts,
                    store.cpu_total,
                    store.cpu_peak,
                    store.mem_total,
                    store.mem_peak,
                )
            ]
            if keys
            else []
        )
        if not ops and self.uplink_epoch <= 0:
            return None
        payload = encode_ops(
            ops,
            epoch=self.uplink_epoch,
            extra={
                "reset": True,
                "window_end": self.state.last_end,
                "kind": "snapshot",
            },
            num_buckets=store.spec.num_buckets,
        )
        return self.uplink_epoch, encode_message(MSG_DELTA, payload)

    # ------------------------------------------------- degraded-tick helpers
    def _step(self) -> float:
        return float(self._step_seconds())

    def _publish_stale_state(self) -> None:
        """Reflect the quarantine into the read side: ``stale_since`` per
        key (the last grid point actually folded) and the gauge."""
        step = self._step()
        self.state.stale_workloads = {
            key: start - step for key, start in self._quarantine.items()
        }
        self.state.metrics.set("krr_tpu_stale_workloads", len(self._quarantine))

    async def _expire_quarantine(self, now: float) -> None:
        """Drop quarantined workloads whose staleness exceeded the budget:
        their accumulated rows leave the store, so they re-enter as FRESH
        (full-window backfill on the next successful fetch) instead of
        carrying an incremental catch-up window the operator no longer
        trusts as "last known good". The compaction copies the [N x B]
        matrix — off the loop, like the discovery compaction."""
        step = self._step()
        expired = [
            key for key, start in self._quarantine.items()
            if now - (start - step) > self.max_staleness
        ]
        if not expired:
            return
        for key in expired:
            del self._quarantine[key]
        dropped = await asyncio.to_thread(
            self.state.store.compact,
            frozenset(self.state.store.keys) - frozenset(expired),
        )
        # Refresh the read side NOW: if this tick later aborts, /healthz and
        # the gauge must not keep counting workloads whose rows are gone.
        self._publish_stale_state()
        self.state.metrics.inc("krr_tpu_quarantine_expired_total", len(expired))
        self.logger.warning(
            f"{len(expired)} quarantined workload(s) exceeded the "
            f"{self.max_staleness:.0f}s staleness budget — dropped {dropped} "
            f"store row(s); they re-enter with a full-window backfill"
        )

    async def _recompute_and_publish(
        self,
        objects: list[K8sObjectData],
        rows: np.ndarray,
        window_end: float,
        *,
        record: bool = True,
    ) -> None:
        """Query the store, gate through hysteresis, journal the raw tick,
        render, publish. ``record=False`` on the resume re-publish (the tick
        was already journaled before the restart)."""
        from krr_tpu.strategies.simple import finalize_fleet

        metrics = self.state.metrics
        journal = self.state.journal

        def render() -> "tuple[Result, bytes, bytes, object, list[str]]":
            # Query + gate + journal + recommend + render + encode in ONE
            # worker-thread hop: the whole-fleet JSON is multi-MB at scale,
            # and any leg of it on the event loop stalls every in-flight
            # query. The store query is the shared
            # `DigestStore.query_recommendation` — the same path the tdigest
            # strategy's run_digested uses, queried exactly once per tick.
            # The quantile/round sub-spans (the serve legs of the compute
            # taxonomy, `krr_tpu.obs.device`) parent to the compute span via
            # the contextvar copied into this worker thread.
            settings = self.session.strategy.settings
            config = self.session.config
            with tracer.span("quantile", rows=len(objects), path="store"):
                cpu_raw, mem_raw = self.state.store.query_recommendation(
                    rows, float(settings.cpu_percentile)
                )
            keys = [object_key(obj) for obj in objects]
            decision = self.gate.observe(keys, cpu_raw, mem_raw)
            # The instantaneous over-provision snapshot (`krr_tpu.eval`):
            # what the gate-HELD values publish above this tick's raw
            # demand, fleet-summed. The /statusz savings block integrates
            # the same slack over the journal window; this pair is the
            # per-tick spot reading. Raw memory is journal-unit MB → GB.
            held_cpu = np.asarray(decision.cpu, np.float64)
            held_mem = np.asarray(decision.mem, np.float64)
            cpu_slack = np.where(
                np.isfinite(held_cpu) & np.isfinite(cpu_raw),
                np.maximum(held_cpu - cpu_raw, 0.0), 0.0,
            )
            mem_slack = np.where(
                np.isfinite(held_mem) & np.isfinite(mem_raw),
                np.maximum(held_mem - mem_raw, 0.0), 0.0,
            )
            metrics.set("krr_tpu_eval_overprovision_cores", round(float(cpu_slack.sum()), 6))
            metrics.set("krr_tpu_eval_overprovision_gb", round(float(mem_slack.sum()) / 1000.0, 6))
            # The shared publish epoch: this tick's journal batch is marked
            # with the epoch its store persist WILL commit as, so a crash
            # between the two is detectable (and reconciled by truncation)
            # at restart instead of heuristically.
            pending_epoch = (
                self.durable.epoch + 1
                if self.durable is not None and self.durable.fmt == "sharded"
                else None
            )
            if journal is not None:
                if record:
                    journal.append_tick(
                        window_end, keys, cpu_raw, mem_raw, decision.published,
                        epoch=pending_epoch,
                    )
                    dropped = journal.compact(window_end)
                    if dropped:
                        metrics.inc("krr_tpu_journal_compacted_records_total", dropped)
                elif self.gate.enabled:
                    # The resume re-publish normally journals nothing (the
                    # window was journaled before the restart) — but rows the
                    # gate publishes FIRST-TIME here (workloads the journal
                    # seed couldn't cover: flagged records aged out, lost
                    # sidecar) must gain a FLAG_PUBLISHED record, or the
                    # journal's forward-filled published series (drift, the
                    # next restart's seed) diverges from what the gate holds.
                    # Excluded: seed-covered rows whose gate happened to open
                    # (published & changed), and any key that ALREADY has a
                    # record at this window_end (its raw tick survived
                    # retention even though its published flag didn't) — a
                    # duplicate same-timestamp record would distort the
                    # /history tick counts and the drift/flap series.
                    first = decision.published & ~decision.changed
                    if bool(np.any(first)):
                        from krr_tpu.history.journal import hash_key

                        recs = journal.records()
                        at_tick = {int(h) for h in recs["key_hash"][recs["ts"] == window_end]}
                        if at_tick:
                            first &= np.fromiter(
                                (hash_key(k) not in at_tick for k in keys), bool, len(keys)
                            )
                    if bool(np.any(first)):
                        idx = np.flatnonzero(first)
                        journal.append_tick(
                            window_end,
                            [keys[i] for i in idx],
                            cpu_raw[idx],
                            mem_raw[idx],
                            np.ones(len(idx), bool),
                            # The resume re-publish persists nothing after:
                            # these records belong to the CURRENT durable
                            # epoch, not a pending one.
                            epoch=(
                                self.durable.epoch
                                if self.durable is not None and self.durable.fmt == "sharded"
                                else None
                            ),
                        )
            with tracer.span("round", rows=len(objects)):
                raw_results = finalize_fleet(
                    decision.cpu, decision.mem, settings.memory_buffer_percentage
                )
                scans = [
                    ResourceScan.calculate(
                        obj,
                        round_allocations(
                            raw,
                            cpu_min_value=config.cpu_min_value,
                            memory_min_value=config.memory_min_value,
                        ),
                    )
                    for obj, raw in zip(objects, raw_results)
                ]
                # Degraded-tick stale marks: a quarantined workload's scan
                # carries the age of its last folded window, so consumers
                # of /recommendations can tell a carried-forward value
                # from a fresh one.
                stale = self.state.stale_workloads
                if stale:
                    for key, scan in zip(keys, scans):
                        since = stale.get(key)
                        if since is not None:
                            scan.stale_since = since
                result = Result(scans=scans)
            body = result.format("json").encode()
            # Digested here, in the worker thread: publish() then decides
            # changed-vs-identical with an O(1) compare under the write
            # lock instead of a fleet-sized memcmp on the event loop.
            import hashlib

            digest = hashlib.blake2b(body, digest_size=16).digest()
            return result, body, digest, decision, keys

        tracer = self.session.tracer
        with tracer.span("compute", rows=len(objects)):
            result, body, digest, decision, keys = await asyncio.to_thread(render)
        with tracer.span("publish") as publish_span:
            changed = int(np.count_nonzero(decision.changed))
            suppressed = int(np.count_nonzero(decision.suppressed))
            if changed:
                metrics.inc("krr_tpu_recommendation_churn_total", changed)
            if suppressed:
                metrics.inc("krr_tpu_hysteresis_suppressed_total", suppressed)
            self.state.last_publish_changed = changed
            self.state.last_publish_suppressed = suppressed
            if journal is not None:
                metrics.set("krr_tpu_journal_records", journal.record_count)
                metrics.set("krr_tpu_journal_bytes", journal.nbytes)
                newest, oldest = journal.newest_ts, journal.oldest_ts
                metrics.set(
                    "krr_tpu_journal_span_seconds",
                    (newest - oldest) if newest is not None and oldest is not None else 0.0,
                )
            publish_span.set(changed=changed, suppressed=suppressed)
            # The epoch and changed_at are stamped by the state's publish:
            # byte-identical republishes (suppressed ticks) keep the
            # previous epoch, so the read path's ETags/cache stay warm.
            await self.state.publish(
                Snapshot(
                    result=result,
                    body_json=body,
                    window_end=window_end,
                    published_at=time.time(),
                    keys=tuple(keys),
                    body_digest=digest,
                )
            )

    async def tick(self) -> bool:
        """One scan: (maybe) re-discover, fetch the due window, fold,
        recompute, publish. Returns False when no new window was due."""
        async with self.state.scan_lock:
            # One trace per tick: the root span's trace_id IS the scan id
            # stamped through structured logs (contextvar propagation),
            # /healthz (last_scan_id), and /debug/trace. Ticks that turn
            # out to be pure no-ops are discarded from the ring below so
            # they can't evict real scans.
            tracer = self.session.tracer
            with tracer.span("scan", kind="serve") as scan_span:
                did_scan = await self._tick_traced(scan_span)
            if not did_scan and scan_span.attributes.get("kind") == "skipped":
                tracer.discard(scan_span.trace_id)
            return did_scan

    async def _federation_tick(self, scan_span) -> bool:
        """The AGGREGATE tick (federation mode): replay queued shard delta
        records into the fleet store — the WAL recovery path on the wire —
        then publish the merged view through the unchanged pipeline (store
        query → hysteresis → journal → render → snapshot swap → durable
        persist). Acks flush only after the persist proves the applied ops
        durable (memory-only serves ack right after apply)."""
        agg = self.aggregator
        now = float(self.clock())
        metrics = self.state.metrics
        tracer = self.session.tracer

        t0 = time.perf_counter()
        stale = agg.stale_marks(now)
        pending = agg.pending_records()
        if (
            not pending
            and not agg.dirty
            and stale == self.state.stale_workloads
            and self.state.peek() is not None
        ):
            metrics.inc("krr_tpu_scans_skipped_total")
            scan_span.set(kind="skipped")
            return False
        agg.dirty = False
        with tracer.span("apply", records=pending):
            applied, applied_bytes = await agg.apply_queued()
        # Lineage stage 3, stamped with THIS process's clock (each hop's
        # own clock keeps the chain monotone under pinned test clocks).
        apply_ts = float(self.clock())
        t1 = time.perf_counter()

        objects = agg.fleet_objects()
        # Re-read AFTER the apply: freshly applied windows un-stale shards.
        stale = agg.stale_marks(now)
        self.state.stale_workloads = stale
        metrics.set("krr_tpu_stale_workloads", len(stale))
        end = agg.newest_window_end() or self.state.last_end or now
        if objects:
            keys = [object_key(obj) for obj in objects]
            rows = await asyncio.to_thread(self.state.store.rows_for, keys)
            await self._recompute_and_publish(objects, rows, end)
        elif not applied:
            # Nothing applied AND nothing to render (no shard has
            # delivered an inventory yet): a pure no-op round.
            metrics.inc("krr_tpu_scans_skipped_total")
            scan_span.set(kind="skipped")
            return False
        # else: ops applied before any inventory arrived (e.g. an
        # aggregator restart mid-reconnect wave) — keep serving whatever is
        # published, but still persist + ack the applied records below.
        # The window cursor advances whenever records applied, published or
        # not, so freshness accounting tracks the applied windows.
        self.state.last_end = end
        t2 = time.perf_counter()

        if self.uplink is not None:
            # Capture BEFORE the persist: save_delta drains the same
            # pending-op queue this encodes from.
            await self._uplink_tick(objects, end)
        persist_seconds = 0.0
        persist_bytes = 0
        if self.state_path:
            wal_before = self.durable.wal_size if self.durable is not None else 0
            await self._persist()
            persist_seconds = time.perf_counter() - t2
            wal_after = self.durable.wal_size if self.durable is not None else 0
            persist_bytes = max(0, wal_after - wal_before)
            if not self.state.persist_failing:
                self._uplink_consumed = 0  # the persist drained the capture
        if not self.state.persist_failing:
            # The applied ops are durable (or serve is memory-only, where
            # apply IS the commit point): release the shards' buffers. A
            # failing persist withholds acks — shards keep their records
            # and the next fault-free tick's persist carries the backlog.
            await agg.flush_acks()
        # Stamp the published epoch's lineage + trace context BEFORE the
        # broadcast, so the feed frame carries both and the replicas'
        # install spans/acks can join this tick. `note_epoch` is the
        # lineage commit point: it fires the fold/apply/publish freshness
        # histograms exactly once per epoch.
        from krr_tpu.obs.trace import propagation_context

        snapshot = self.state.peek()
        publish_ts = float(self.clock())
        lineage = agg.note_epoch(
            snapshot.epoch if snapshot is not None else 0,
            apply_ts=apply_ts,
            publish_ts=publish_ts,
            trace_ctx=propagation_context(scan_span, node=agg.node),
        )
        # Push this tick's published epoch to subscribed read replicas
        # (no-op when the epoch didn't move or nothing is published yet —
        # the frame still refreshes so late subscribers catch up warm).
        await agg.broadcast_epoch()
        if self.uplink is not None:
            await self.uplink.pump()

        metrics.inc("krr_tpu_scans_total", kind="aggregate")
        metrics.set("krr_tpu_last_scan_timestamp_seconds", end)
        metrics.set("krr_tpu_scan_duration_seconds", 0.0, phase="discover")
        metrics.set("krr_tpu_scan_duration_seconds", 0.0, phase="fetch")
        metrics.set("krr_tpu_scan_duration_seconds", t1 - t0, phase="fold")
        metrics.set("krr_tpu_scan_duration_seconds", t2 - t1, phase="compute")
        metrics.set("krr_tpu_digest_store_rows", len(self.state.store.keys))
        metrics.set("krr_tpu_digest_store_bytes", self.state.store.nbytes)
        agg.tick_gauges(now)
        agg.fleet_gauges(now)
        federation_stats = agg.tick_stats(now, applied)
        # The timeline's lineage block: this epoch's hops, plus the newest
        # REPLICA-ACKED epoch's install hop (acks land after the tick that
        # published, so the install stage intentionally trails — the
        # sentinel bands it against its own epoch's publish_ts).
        timeline_lineage = dict(lineage) if lineage is not None else None
        if timeline_lineage is not None:
            timeline_lineage.pop("installs", None)
            installed_record = agg.newest_installed_lineage()
            if installed_record is not None:
                timeline_lineage["install"] = {
                    "epoch": installed_record.get("epoch"),
                    "install_ts": installed_record.get("install_ts"),
                    "publish_ts": installed_record.get("publish_ts"),
                    "replicas": len(installed_record.get("installs") or {}),
                }
        scan_span.set(
            kind="aggregate",
            window_end=end,
            objects=len(objects),
            applied_records=applied,
            shards=federation_stats["shards"],
            stale_shards=federation_stats["stale_shards"],
        )
        self.state.last_scan_id = scan_span.trace_id
        self.last_tick_stats = {
            "scan_id": scan_span.trace_id,
            "kind": "aggregate",
            "window_start": end,
            "window_end": end,
            "objects": len(objects),
            "failed_rows": 0,
            "backfilled": 0,
            "stale": len(stale),
            "publish_changed": self.state.last_publish_changed,
            "publish_suppressed": self.state.last_publish_suppressed,
            "persist_seconds": persist_seconds,
            "persist_bytes": persist_bytes,
            "persist_failing": self.state.persist_failing,
            "epoch": (
                self.durable.epoch
                if self.durable is not None and self.durable.fmt == "sharded"
                else None
            ),
            "federation": federation_stats,
        }
        if timeline_lineage is not None:
            self.last_tick_stats["lineage"] = timeline_lineage
        self.logger.info(
            f"aggregate tick {scan_span.trace_id or ''} applied {applied} shard "
            f"record(s) ({applied_bytes} B) from "
            f"{federation_stats['connected']}/{federation_stats['shards']} connected "
            f"shard(s) ({len(self.state.store.keys)} store rows, "
            f"{len(stale)} stale workload(s)): apply {t1 - t0:.2f}s, "
            f"compute {t2 - t1:.2f}s"
        )
        return True

    async def _tick_traced(self, scan_span) -> bool:
        from krr_tpu.strategies.simple import MEMORY_SCALE

        if self.aggregator is not None:
            return await self._federation_tick(scan_span)

        now = float(self.clock())
        metrics = self.state.metrics
        settings = self.session.strategy.settings
        step = self._step_seconds()
        # Fresh per-scan fetch budgets (the Prometheus retry deadline pool).
        self.session.begin_scan()

        t0 = time.perf_counter()
        # Watch mode reconciles EVERY tick — the whole point of the resident
        # inventory is that re-discovery became O(churn) in-memory work, so
        # workload churn lands on the next scan instead of the next
        # discovery interval.
        if (
            self._objects is None
            or now - self._discovered_at >= self.discovery_interval
            or self.discovery_mode == "watch"
        ):
            await self._discover(now)
        objects = self._objects or []
        t1 = time.perf_counter()

        if self.state.last_end is None:
            start = now - settings.history_timedelta.total_seconds()
            if getattr(self.session.config, "fetch_downsample", "off") != "off":
                # Server-side downsampling is only exact on the ABSOLUTE
                # step grid (Prometheus evaluates subquery inner steps at
                # epoch-aligned timestamps): align the first window's origin
                # down to it. Every later edge inherits the alignment —
                # delta starts are last_end + step, backfill/catch-up edges
                # derive from the aligned end. Costs at most one extra step
                # of history on the first full scan.
                start -= start % step
            kind = "full"
        else:
            # One step past the last folded window's right edge: the
            # range query's grid includes its own start point, so
            # starting AT last_end would re-fetch (and double-count)
            # the sample already folded there.
            start = self.state.last_end + step
            kind = "delta"
            if start > now:
                metrics.inc("krr_tpu_scans_skipped_total")
                scan_span.set(kind="skipped")
                if self.state.peek() is None and self.state.store.keys:
                    scan_span.set(kind="resume-publish")
                    # A state_path restart inside one step window: the
                    # resumed store is complete but nothing is published
                    # yet — serve from the resident digests instead of
                    # 503ing until the next window opens. Only objects
                    # ALREADY resident are published: rows_for grows
                    # empty rows for unseen keys, and inserting a
                    # workload discovered while the server was down
                    # would make the next tick see it as seasoned and
                    # skip its full-window backfill forever — it joins
                    # the published result when that tick runs instead.
                    known = [
                        obj for obj in objects if object_key(obj) in self.state.store
                    ]
                    rows = await asyncio.to_thread(
                        self.state.store.rows_for, [object_key(obj) for obj in known]
                    )
                    # record=False: this window's tick was journaled
                    # before the restart — re-appending it would
                    # double-record the same timestamp.
                    await self._recompute_and_publish(
                        known, rows, self.state.last_end, record=False
                    )
                    self.state.last_scan_id = scan_span.trace_id
                return False
        # Clamp the right edge to the last evaluation-grid point ≤ now
        # (see the module docstring): the next delta then starts exactly
        # one step past the last point actually fetched.
        end = start + ((now - start) // step) * step

        # A full scan refetches everything from scratch — any quarantine
        # inherited from stale metadata is covered by it.
        if kind == "full" and self._quarantine:
            self._quarantine.clear()
            self._publish_stale_state()
        # Quarantined workloads past the staleness budget drop their rows
        # and re-enter as fresh (full backfill) — BEFORE the leg split, so
        # they land in `fresh` below.
        await self._expire_quarantine(now)

        # Leg split. Workloads that appeared since the last scan have no
        # store row yet; a delta-width fetch would skip everything between
        # their creation and last_end (startup spikes included — peak-based
        # memory recommendations would miss them forever). They get a
        # FULL-window backfill alongside the fleet's delta. QUARANTINED
        # workloads (an earlier degraded tick lost their window) instead get
        # a CATCH-UP leg from their own cursor — the union of every window
        # they missed plus this delta, which the digest's exact mergeability
        # folds bit-identically to having never missed them.
        backfill_start = end - (settings.history_timedelta.total_seconds() // step) * step
        fresh: list[K8sObjectData] = []
        seasoned: list[K8sObjectData] = []
        catchup: dict[float, list[K8sObjectData]] = {}
        if kind == "delta":
            for obj in objects:
                key = object_key(obj)
                if key in self._quarantine:
                    catchup.setdefault(self._quarantine[key], []).append(obj)
                elif key not in self.state.store:
                    fresh.append(obj)
                else:
                    seasoned.append(obj)
        else:
            seasoned = objects

        # Push-fed leg (--metrics-mode push): seasoned workloads whose
        # buffered remote-write streams COVER [start, end] — every pod
        # series of both resources joined before the window and watermarked
        # past its end — fold from the plane with ZERO range queries.
        # Anything the watermarks can't vouch for (a listener outage, a
        # late-joining series, a shed buffer) stays on the range legs: the
        # gap-backfill arm of the ladder.
        push_objs: list[K8sObjectData] = []
        if self.ingest is not None and kind == "delta" and seasoned:
            range_objs: list[K8sObjectData] = []
            for obj in seasoned:
                (
                    push_objs
                    if self.ingest.push_ready(obj, start, end)
                    else range_objs
                ).append(obj)
            seasoned = range_objs

        use_pipeline = self.session.config.pipeline_depth > 0
        pipeline_stats = []

        async def fetch(objs: list[K8sObjectData], w_start: float) -> "object":
            if use_pipeline:
                # Streamed pipeline: per-namespace batches fold into the
                # tick's PRIVATE window fleet while the rest still fetch
                # (`ScanSession.stream_fleet_digests`). The resident
                # store is only touched by the single fold below — a
                # failed BATCH degrades to empty rows marked in
                # failed_rows (quarantine fodder), and an aborted tick
                # still leaves the store untouched.
                _objs, fleet, stats = await self.session.stream_fleet_digests(
                    objs,
                    history_seconds=end - w_start,
                    step_seconds=settings.timeframe_timedelta.total_seconds(),
                    end_time=end,
                    raise_on_failure=False,
                )
                pipeline_stats.append(stats)
                return fleet
            return await self.session.gather_fleet_digests(
                objs,
                history_seconds=end - w_start,
                step_seconds=settings.timeframe_timedelta.total_seconds(),
                end_time=end,
                raise_on_failure=False,
            )

        legs: list[tuple[list[K8sObjectData], float, str]] = []
        has_seasoned_leg = bool(seasoned) or not (fresh or catchup or push_objs)
        if has_seasoned_leg:
            legs.append((seasoned, start, kind))
        if fresh:
            legs.append((fresh, backfill_start, "backfill"))
        for q_start in sorted(catchup):
            legs.append((catchup[q_start], q_start, "catchup"))
        # return_exceptions so a failing fetch doesn't orphan its
        # sibling mid-download (same rationale as the session's own
        # cluster fan-out). Only infrastructure errors arrive here now —
        # fetch failures degrade to failed_rows.
        fleets = await asyncio.gather(
            *[fetch(leg_objects, w_start) for leg_objects, w_start, _ in legs],
            return_exceptions=True,
        )
        for fleet in fleets:
            if isinstance(fleet, BaseException):
                raise fleet

        # Fold the push-fed leg from the plane's buffered streams: the same
        # grid, digest arithmetic, and merge semantics as a range fetch of
        # [start, end] — bit-exactness is the contract, audited below.
        ingest_tick: "Optional[dict]" = None
        if self.ingest is not None:
            ingest_tick = await self._ingest_fold(
                objects, push_objs, start, end, step, now, fleets
            )
        t2 = time.perf_counter()

        # Fault isolation: failed workloads QUARANTINE (their windows stay
        # unfolded; last-good digests carry forward below) — unless the
        # fetch-success fraction falls under the floor, where publishing
        # the mostly-empty remainder would be worse than serving the
        # previous result.
        failed_keys: set[str] = set()
        for fleet in fleets:
            for i in fleet.failed_rows:
                failed_keys.add(object_key(fleet.objects[i]))
        if objects and failed_keys:
            success_pct = 100.0 * (1.0 - len(failed_keys) / len(objects))
            if success_pct < self.min_fetch_success_pct:
                raise RuntimeError(
                    f"{len(failed_keys)} of {len(objects)} object fetches failed "
                    f"terminally (fetch success {success_pct:.0f}% below the "
                    f"--min-fetch-success-pct floor {self.min_fetch_success_pct:g}%)"
                )

        with self.session.tracer.span("fold", rows=len(objects)):
            for fleet in fleets:
                if fleet.failed_rows:
                    # A failed row may still carry ONE resource's successful
                    # samples (its sibling query failed). Zero it entirely:
                    # the catch-up leg refetches BOTH resources over the
                    # missed windows, and a half-folded row would
                    # double-count the surviving half.
                    rows_to_clear = sorted(fleet.failed_rows)
                    fleet.clear_cpu_rows(rows_to_clear)
                    fleet.clear_mem_rows(rows_to_clear)
                await asyncio.to_thread(self.state.store.fold_fleet, fleet, MEMORY_SCALE)
            rows = await asyncio.to_thread(
                self.state.store.rows_for, [object_key(obj) for obj in objects]
            )
        self.state.last_end = end

        # Quarantine bookkeeping: recovered workloads (their catch-up leg
        # folded through `end`) leave; newly failed ones enter at their
        # leg's window start; repeat offenders keep their ORIGINAL cursor —
        # the catch-up window keeps growing until it succeeds or expires.
        for leg_objects, w_start, _ in legs:
            for obj in leg_objects:
                key = object_key(obj)
                if key in failed_keys:
                    self._quarantine.setdefault(key, w_start)
                else:
                    self._quarantine.pop(key, None)
        self._publish_stale_state()
        if failed_keys:
            metrics.inc("krr_tpu_scans_degraded_total")
            metrics.inc("krr_tpu_fetch_failed_rows_total", len(failed_keys))
            self.logger.warning(
                f"Degraded tick: {len(failed_keys)} of {len(objects)} workload "
                f"fetches failed — quarantined with stale marks "
                f"({len(self._quarantine)} total in quarantine)"
            )
        metrics.set("krr_tpu_scan_failed_rows", len(failed_keys))
        if pipeline_stats:
            # Batch-granular failure view (between per-row failed_keys and
            # the per-tick degraded counter): how many namespace batches
            # came back dead this tick.
            metrics.set(
                "krr_tpu_scan_failed_batches",
                sum(s.failed_batches for s in pipeline_stats),
            )
        t3 = time.perf_counter()

        await self._recompute_and_publish(objects, rows, end)
        t4 = time.perf_counter()

        persist_seconds = 0.0
        persist_bytes = 0
        if self.state_path:
            wal_before = self.durable.wal_size if self.durable is not None else 0
            await self._persist()
            persist_seconds = time.perf_counter() - t4
            # Appended WAL bytes (clamped: a threshold compaction inside
            # the persist resets the WAL, which is not a negative append).
            wal_after = self.durable.wal_size if self.durable is not None else 0
            persist_bytes = max(0, wal_after - wal_before)

        metrics.inc("krr_tpu_scans_total", kind=kind)
        # Every object's fetch was ATTEMPTED this tick — the SLO fetch
        # objective's denominator (failed ones landed in
        # krr_tpu_fetch_failed_rows_total above).
        if objects:
            metrics.inc("krr_tpu_fetch_rows_total", len(objects))
        if has_seasoned_leg:
            # Only when the delta/full leg actually fetched: a tick whose
            # every object rode a backfill or catch-up leg counts those
            # windows under their own kinds, not a phantom delta.
            metrics.inc("krr_tpu_fetch_window_seconds_total", end - start, kind=kind)
        if fresh:
            metrics.inc("krr_tpu_backfilled_objects_total", len(fresh))
            metrics.inc(
                "krr_tpu_fetch_window_seconds_total", end - backfill_start, kind="backfill"
            )
        for q_start in catchup:
            metrics.inc(
                "krr_tpu_fetch_window_seconds_total", end - q_start, kind="catchup"
            )
        metrics.set("krr_tpu_scan_window_seconds", end - start)
        metrics.set("krr_tpu_last_scan_timestamp_seconds", end)
        metrics.set("krr_tpu_scan_duration_seconds", t1 - t0, phase="discover")
        metrics.set("krr_tpu_scan_duration_seconds", t2 - t1, phase="fetch")
        metrics.set("krr_tpu_scan_duration_seconds", t3 - t2, phase="fold")
        metrics.set("krr_tpu_scan_duration_seconds", t4 - t3, phase="compute")
        if pipeline_stats:
            # Per-stage overlap of the streamed fetch+fold pipeline —
            # the main (seasoned) leg plus any backfill leg, summed for
            # busy time, max'd for the overlap percentage.
            metrics.set(
                "krr_tpu_scan_pipeline_seconds",
                sum(s.fetch_seconds for s in pipeline_stats),
                stage="fetch",
            )
            metrics.set(
                "krr_tpu_scan_pipeline_seconds",
                sum(s.fold_seconds for s in pipeline_stats),
                stage="fold",
            )
            metrics.set(
                "krr_tpu_scan_overlap_pct",
                max(s.overlap_pct for s in pipeline_stats),
            )
            # Wait attribution: which pipeline side gated this tick
            # (producers blocked in put = fold-bound, consumer starved in
            # get = fetch-bound), summed like the stage busy times.
            metrics.set(
                "krr_tpu_scan_pipeline_wait_seconds",
                sum(s.put_blocked_seconds for s in pipeline_stats),
                side="producer_blocked",
            )
            metrics.set(
                "krr_tpu_scan_pipeline_wait_seconds",
                sum(s.get_starved_seconds for s in pipeline_stats),
                side="consumer_starved",
            )
        metrics.set("krr_tpu_digest_store_rows", len(self.state.store.keys))
        metrics.set("krr_tpu_digest_store_bytes", self.state.store.nbytes)
        scan_span.set(
            kind=kind,
            window_start=start,
            window_end=end,
            objects=len(objects),
            backfilled=len(fresh),
            failed_rows=len(failed_keys),
            quarantined=len(self._quarantine),
        )
        self.state.last_scan_id = scan_span.trace_id
        self.last_tick_stats = {
            "scan_id": scan_span.trace_id,
            "kind": kind,
            "window_start": start,
            "window_end": end,
            "objects": len(objects),
            "failed_rows": len(failed_keys),
            "backfilled": len(fresh),
            "stale": len(self._quarantine),
            "discovery": self._discovery_tick_stats(now),
            "ingest": ingest_tick,
            "publish_changed": self.state.last_publish_changed,
            "publish_suppressed": self.state.last_publish_suppressed,
            "persist_seconds": persist_seconds,
            "persist_bytes": persist_bytes,
            "persist_failing": self.state.persist_failing,
            "epoch": (
                self.durable.epoch
                if self.durable is not None and self.durable.fmt == "sharded"
                else None
            ),
        }
        self.logger.info(
            f"{kind} scan {scan_span.trace_id or ''} folded window [{start:.0f}, {end:.0f}] "
            f"({len(objects)} objects, {len(self.state.store.keys)} store rows): "
            f"discover {t1 - t0:.2f}s, fetch {t2 - t1:.2f}s, "
            f"fold {t3 - t2:.2f}s, compute {t4 - t3:.2f}s"
        )
        return True

    # ------------------------------------------------- push-ingest fold
    async def _ingest_fold(
        self,
        objects: "list[K8sObjectData]",
        push_objs: "list[K8sObjectData]",
        start: float,
        end: float,
        step: float,
        now: float,
        fleets: list,
    ) -> dict:
        """Fold the push-fed leg and (on the audit cadence) verify it
        against a range-fetched ground truth.

        The audit mirrors the discovery audit's ladder: every
        ``--ingest-verify-interval`` seconds the push-folded rows are ALSO
        range-fetched over the same window and compared exactly — counts,
        totals, peaks, bit for bit. Divergent rows are counted, REPAIRED by
        adopting the range rows into this tick's fold, and their buffered
        series invalidated so the next tick range-backfills them fresh."""
        metrics = self.state.metrics
        settings = self.session.strategy.settings
        spec = settings.cpu_spec()
        verify: "Optional[dict]" = None
        if push_objs:
            key_to_row = {object_key(o): i for i, o in enumerate(objects)}
            push_rows = [key_to_row[object_key(o)] for o in push_objs]
            push_fleet = await asyncio.to_thread(
                self.ingest.fold_fleet,
                objects,
                push_rows,
                start,
                end,
                step,
                spec.gamma,
                spec.min_value,
                spec.num_buckets,
            )
            if now - self._last_ingest_verify_at >= self.ingest_verify_interval:
                self._last_ingest_verify_at = now
                metrics.inc("krr_tpu_ingest_verify_total")
                control = await self.session.gather_fleet_digests(
                    push_objs,
                    history_seconds=end - start,
                    step_seconds=settings.timeframe_timedelta.total_seconds(),
                    end_time=end,
                    raise_on_failure=False,
                )
                audited = divergent = 0
                for j, obj in enumerate(push_objs):
                    if j in control.failed_rows:
                        continue  # no ground truth for this row this round
                    audited += 1
                    i = push_rows[j]
                    if (
                        np.array_equal(push_fleet.cpu_counts[i], control.cpu_counts[j])
                        and push_fleet.cpu_total[i] == control.cpu_total[j]
                        and push_fleet.cpu_peak[i] == control.cpu_peak[j]
                        and push_fleet.mem_total[i] == control.mem_total[j]
                        and push_fleet.mem_peak[i] == control.mem_peak[j]
                    ):
                        continue
                    divergent += 1
                    metrics.inc("krr_tpu_ingest_verify_divergences_total")
                    # Repair: this tick folds the RANGE row (ground truth),
                    # and the diverged buffers drop so the next window
                    # range-backfills instead of re-folding bad samples.
                    push_fleet.cpu_counts[i] = control.cpu_counts[j]
                    push_fleet.cpu_total[i] = control.cpu_total[j]
                    push_fleet.cpu_peak[i] = control.cpu_peak[j]
                    push_fleet.mem_total[i] = control.mem_total[j]
                    push_fleet.mem_peak[i] = control.mem_peak[j]
                    self.ingest.invalidate_object(obj)
                    self.logger.warning(
                        f"Ingest audit: push-fed window diverged from range "
                        f"ground truth for {object_key(obj)} — repaired from "
                        f"the range fetch, buffers invalidated"
                    )
                verify = {"audited": audited, "divergent": divergent}
            fleets.append(push_fleet)
            metrics.inc("krr_tpu_ingest_push_objects_total", len(push_objs))
        # Retention: folded windows never look back past the lookback from
        # the window's right edge — keep one full lookback of slack.
        await asyncio.to_thread(
            self.ingest.prune, int(round((end - self.ingest.lookback_ms / 1000.0) * 1000.0))
        )
        stats = self.ingest.stats()
        freshness = self.ingest.freshness_seconds(now)
        metrics.set("krr_tpu_ingest_series", stats["series"])
        metrics.set("krr_tpu_ingest_buffered_samples", stats["buffered_samples"])
        if freshness is not None:
            metrics.set("krr_tpu_ingest_freshness_seconds", freshness)
        tick = {
            "mode": "push",
            "push_objects": len(push_objs),
            "verify": verify,
            "freshness_seconds": freshness,
            "series": stats["series"],
            "buffered_samples": stats["buffered_samples"],
            "samples_total": stats["samples_total"],
            "rejected": stats["rejected"],
        }
        # Refresh the /healthz + /statusz posture in place (the listener's
        # bound port, set at start, rides along untouched).
        self.state.ingest.update(tick)
        return tick

    # ----------------------------------------------- discovery tick stats
    def _discovery_tick_stats(self, now: float) -> dict:
        """Per-tick discovery posture for the timeline record, /healthz, and
        /statusz: the active mode, this tick's watch event deltas
        (adds/updates/drops/bookmarks), watch restarts and relist fallbacks
        since the last tick, and the inventory/watch freshness ages."""
        metrics = self.state.metrics
        inventory = self.session.get_inventory()
        status_fn = getattr(inventory, "discovery_status", None)
        status = status_fn() if callable(status_fn) else {}

        def events_total(type_: str) -> float:
            return sum(
                value
                for series, value in metrics.series(
                    "krr_tpu_discovery_watch_events_total"
                ).items()
                if ("type", type_) in set(series)
            )

        totals = {
            "adds": events_total("added"),
            "updates": events_total("modified"),
            "drops": events_total("deleted"),
            "bookmarks": events_total("bookmark"),
            "watch_restarts": metrics.total("krr_tpu_discovery_watch_restarts_total"),
            "relists": metrics.total("krr_tpu_discovery_relists_total"),
        }
        delta = {
            key: int(max(0.0, value - self._discovery_totals.get(key, 0.0)))
            for key, value in totals.items()
        }
        self._discovery_totals = totals
        stats: dict = {"mode": status.get("mode", self.discovery_mode), **delta}
        if self._discovered_at > -float("inf"):
            stats["inventory_age_seconds"] = round(max(0.0, now - self._discovered_at), 3)
        if status.get("watch_lag_seconds") is not None:
            stats["watch_lag_seconds"] = status["watch_lag_seconds"]
        # The read side (/healthz, /statusz) shows the LIVE posture.
        self.state.discovery = dict(stats)
        return stats

    # ----------------------------------------------- read-path tick stats
    def _readpath_tick_stats(self) -> dict:
        """Per-tick /recommendations serving stats from the shared registry:
        requests/304s/cache hits/misses/sheds/bytes as deltas since the
        last recorded tick, plus the tick's p99 request latency estimated
        from the route's histogram-bucket deltas. Feeds the timeline record
        (so the sentinel can band read latency), the
        ``krr_tpu_http_read_p99_seconds`` gauge (the optional
        ``--slo-read-p99`` objective's value), and the
        ``krr_tpu_http_read_requests`` gauge that gates both on "did this
        tick actually serve reads"."""
        from krr_tpu.obs.metrics import histogram_quantile

        metrics = self.state.metrics
        route = ("route", "/recommendations")

        def route_sum(name: str, **extra: str) -> float:
            want = {route, *((k, v) for k, v in extra.items())}
            return sum(
                value
                for series, value in metrics.series(name).items()
                if want <= set(series)
            )

        totals = {
            "requests": route_sum("krr_tpu_http_requests_total"),
            "not_modified": route_sum("krr_tpu_http_requests_total", code="304"),
            "bytes": route_sum("krr_tpu_http_response_bytes_total"),
            "cache_hits": metrics.total("krr_tpu_http_cache_hits_total"),
            "cache_misses": metrics.total("krr_tpu_http_cache_misses_total"),
            "renders_shed": metrics.total("krr_tpu_http_renders_shed_total"),
        }
        delta = {
            key: max(0.0, value - self._read_totals.get(key, 0.0))
            for key, value in totals.items()
        }
        self._read_totals = totals
        buckets = metrics.histogram_buckets(
            "krr_tpu_http_request_seconds", route="/recommendations"
        )
        p99 = None
        if buckets:
            previous = self._read_buckets or {}
            # Cumulative-minus-cumulative stays cumulative: the diff pairs
            # are this tick's own histogram.
            tick_pairs = [
                (bound, count - previous.get(bound, 0.0)) for bound, count in buckets
            ]
            self._read_buckets = dict(buckets)
            p99 = histogram_quantile(tick_pairs, 0.99)
        stats = {
            "requests": int(delta["requests"]),
            "not_modified": int(delta["not_modified"]),
            "cache_hits": int(delta["cache_hits"]),
            "cache_misses": int(delta["cache_misses"]),
            "shed": int(delta["renders_shed"]),
            "bytes": int(delta["bytes"]),
            "p99_ms": round(p99 * 1e3, 3) if p99 is not None else None,
        }
        metrics.set("krr_tpu_http_read_requests", stats["requests"])
        if stats["requests"] and p99 is not None:
            metrics.set("krr_tpu_http_read_p99_seconds", p99)
        return stats

    # ----------------------------------------------- flight recorder hook
    async def _observe_timeline(self) -> None:
        """Distill the just-completed tick into one timeline record (from
        the trace ring's newest trace + the tick stash), append it to the
        flight recorder, and run the sentinel's classification. Failures
        here degrade — the recorder must never take down the scan loop it
        is recording."""
        timeline = self.state.timeline
        sentinel = self.state.sentinel
        stats = self.last_tick_stats
        if (timeline is None and sentinel is None) or stats is None:
            return
        if stats.get("scan_id") != self.state.last_scan_id:
            return  # stale stash (defensive: the tick aborted after stashing)
        from krr_tpu.obs.profile import profile_trace
        from krr_tpu.obs.timeline import build_scan_record

        report = None
        for spans in reversed(self.session.tracer.traces()):
            if spans and spans[0].trace_id == stats["scan_id"]:
                report = profile_trace(spans)
                break
        metrics = self.state.metrics
        plan_delta: dict[str, float] = {}
        for key, metric in (
            ("coalesced", "krr_tpu_fetch_plan_coalesced_total"),
            ("sharded", "krr_tpu_fetch_plan_sharded_total"),
            ("downsampled", "krr_tpu_fetch_downsampled_total"),
        ):
            total = metrics.total(metric)
            plan_delta[key] = max(0.0, total - self._plan_totals[key])
            self._plan_totals[key] = total
        record = build_scan_record(
            report, stats, metrics=metrics, slo=self.state.slo, plan_delta=plan_delta
        )
        self.last_tick_stats = None
        if timeline is not None:
            # The append fsyncs: off the loop like every other disk leg.
            await asyncio.to_thread(timeline.append, record)
        if sentinel is not None:
            sentinel.observe(record)

    # ----------------------------------------------------------- the loop
    async def run_once(self) -> "Optional[bool]":
        """One guarded scheduler round: tick, count a failure if it aborts,
        record the completed tick into the flight recorder (and classify it
        through the sentinel), then evaluate the SLO engine — failures
        included, which is the point: the burn-rate windows must see bad
        ticks the moment they happen, not whenever the next healthy tick
        lands. Returns the tick's result (None when it failed)."""
        did_scan: Optional[bool] = None
        try:
            did_scan = await self.tick()
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self.state.metrics.inc("krr_tpu_scan_failures_total")
            self.state.consecutive_scan_failures += 1
            self.state.last_scan_error = f"{type(e).__name__}: {e}"[:300]
            self.logger.warning(f"Scan failed: {e} — serving the previous result")
            self.logger.debug_exception()
        else:
            self.state.consecutive_scan_failures = 0
        if did_scan:
            # Stash the tick's read-path serving stats BEFORE the recorder
            # distills them: the timeline record (and through it the
            # sentinel's read_p99_ms band) and the read-p99 SLO gauge both
            # ride this delta.
            if self.last_tick_stats is not None:
                self.last_tick_stats["readpath"] = self._readpath_tick_stats()
            try:
                await self._observe_timeline()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                self.logger.warning(f"Scan timeline recording failed: {e}")
                self.logger.debug_exception()
        # Sentinel verdicts land BEFORE the SLO evaluation so the optional
        # scan_regressions objective sees this tick's classification.
        if self.state.slo is not None:
            self.state.slo.evaluate()
        return did_scan

    async def run(self) -> None:
        while True:
            await self.run_once()
            await asyncio.sleep(self.scan_interval)

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.create_task(self.run(), name="krr-tpu-scan-scheduler")

    async def stop(self) -> None:
        """Graceful shutdown: cancel the loop (a scan cancelled mid-fetch
        leaves the store and published snapshot untouched — ``last_end``
        advances only after a completed fold) and wait for it to unwind."""
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
            self._task = None
