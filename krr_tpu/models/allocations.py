"""Resource types and request/limit allocations.

Behavior-compatible with `/root/reference/robusta_krr/core/models/allocations.py:13-81`
(ported to pydantic v2, which is what this image ships):

* ``ResourceType`` is a string enum {cpu, memory}; adding a member here makes
  the new resource flow through the whole pipeline (severity, formatters, CLI).
* ``RecommendationValue`` is ``Decimal | "?" | None`` — ``None`` means "not
  set / not recommended", ``"?"`` means "unknown" (e.g. no usage data), and a
  Decimal is an absolute value in base units (cores / bytes).
* Parsing accepts k8s quantity strings (``"100m"``, ``"128Mi"``); ``NaN``
  Decimals normalize to ``"?"``.
* JSON serialization renders Decimals as floats (matching the reference's
  pydantic-v1 output so downstream consumers of ``-f json`` see numbers).
"""

from __future__ import annotations

import enum
from decimal import Decimal
from typing import Any, Literal, Mapping, Union

import pydantic as pd
from pydantic import ConfigDict, field_validator
from pydantic.functional_serializers import PlainSerializer
from typing_extensions import Annotated

from krr_tpu.utils import resource_units


class ResourceType(str, enum.Enum):
    """The resource dimensions being recommended. New members are automatically
    supported end-to-end (same contract as the reference's enum comment)."""

    CPU = "cpu"
    Memory = "memory"


def _decimal_to_json(value: Decimal) -> Union[float, str]:
    # NaN should have been normalized to "?" by validators; guard anyway since
    # strict JSON has no NaN literal.
    if value.is_nan():
        return "?"
    return float(value)


#: Decimal that serializes to a JSON number.
JsonDecimal = Annotated[Decimal, PlainSerializer(_decimal_to_json, when_used="json")]

RecommendationValue = Union[JsonDecimal, Literal["?"], None]


def parse_resource_value(value: Union[Decimal, float, int, str, None]) -> RecommendationValue:
    """Normalize a raw allocation value: strings parse as k8s quantities,
    NaN becomes ``"?"``, None passes through."""
    if value is None:
        return None
    if isinstance(value, str):
        if value == "?":
            return "?"
        return resource_units.parse(value)
    if not isinstance(value, Decimal):
        value = Decimal(str(value))
    if value.is_nan():
        return "?"
    return value


class ResourceAllocations(pd.BaseModel):
    """Requests and limits per resource type (current or recommended)."""

    model_config = ConfigDict(frozen=False)

    requests: dict[ResourceType, RecommendationValue]
    limits: dict[ResourceType, RecommendationValue]

    @field_validator("requests", "limits", mode="before")
    @classmethod
    def _parse_values(cls, value: Mapping[Any, Any]) -> dict[Any, Any]:
        return {rt: parse_resource_value(v) for rt, v in value.items()}

    @classmethod
    def from_container_spec(cls, container: Mapping[str, Any]) -> "ResourceAllocations":
        """Build from a raw k8s container spec dict (the ``containers[]`` entry
        of a pod template, as returned by the apiserver JSON API).

        Mirrors ``ResourceAllocations.from_container``
        (`/root/reference/robusta_krr/core/models/allocations.py:53-81`), which
        consumed a kubernetes-client ``V1Container``; we consume plain JSON.
        """
        resources: Mapping[str, Any] = container.get("resources") or {}
        requests: Mapping[str, Any] = resources.get("requests") or {}
        limits: Mapping[str, Any] = resources.get("limits") or {}
        # model_construct + explicit parse_resource_value IS this model's
        # whole validation (the `_parse_values` validator applies exactly
        # that function) — skipping pydantic's validation machinery here
        # was worth ~2 s of the 100k discovery wall.
        return cls.model_construct(
            requests={
                ResourceType.CPU: parse_resource_value(requests.get("cpu")),
                ResourceType.Memory: parse_resource_value(requests.get("memory")),
            },
            limits={
                ResourceType.CPU: parse_resource_value(limits.get("cpu")),
                ResourceType.Memory: parse_resource_value(limits.get("memory")),
            },
        )


NONE_ALLOCATIONS = ResourceAllocations(
    requests={ResourceType.CPU: None, ResourceType.Memory: None},
    limits={ResourceType.CPU: None, ResourceType.Memory: None},
)
