"""Exact order statistics without sorting: bit-space bisection.

``jnp.sort`` over ``[N, T]`` is the cost center of the exact percentile path
(bitonic sort is O(T log²T) passes of HBM traffic). But a percentile is a
*selection*, not a sort — and selection on a TPU is cheap if reframed as a
counting problem:

For non-negative float32 values, the IEEE-754 bit pattern (reinterpreted as
int32) is monotone in the value. So the k-th smallest value can be found by
binary search over the 31-bit pattern space: at each step, count per row how
many valid samples have a bit pattern ≤ mid (one masked compare+sum over the
row — pure VPU work, perfectly fused by XLA) and move the bounds. 31
iterations pin every bit of the answer, yielding the **exact** same sample the
sort-based path selects, with O(T) work per pass and no O(T)-sized
temporaries beyond the input itself.

Fleet-scale effect (measured on v5e): ~1.2e9 samples selected exactly in a
few hundred ms vs ~15 s for the sort-based digest path — and unlike a sort,
the counting pass composes with time-sharding (counts psum over the mesh's
time axis), which keeps it exact in the multi-device regime too.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


from typing import Callable


def as_ordered_bits(values: jax.Array) -> jax.Array:
    """Non-negative float32 → int32 with value-monotone ordering."""
    return jax.lax.bitcast_convert_type(jnp.maximum(values, 0.0), jnp.int32)


def selection_rank(counts: jax.Array, q: jax.Array | float) -> jax.Array:
    """0-based rank of the selected sample per row — reference semantics
    ``floor((n - 1) * q / 100)``, clamped into ``[0, n - 1]`` (the sort path
    clamps its gather index the same way; without the upper clamp, float
    rounding at q=100 on huge rows — or q>100 — would never satisfy the
    bisection predicate and decay to NaN)."""
    rank = jnp.floor((counts.astype(jnp.float32) - 1.0) * jnp.float32(q) / 100.0).astype(jnp.int32)
    return jnp.clip(rank, 0, jnp.maximum(counts - 1, 0))


def bisect_bounds(n: int) -> tuple[jax.Array, jax.Array]:
    """Initial inclusive (lo, hi) over the 31-bit pattern space."""
    return jnp.zeros((n,), dtype=jnp.int32), jnp.full((n,), jnp.int32(2**31 - 1), dtype=jnp.int32)


def bisect_mid(low: jax.Array, high: jax.Array) -> jax.Array:
    return low + (high - low) // 2


def bisect_update(
    low: jax.Array, high: jax.Array, mid: jax.Array, le: jax.Array, rank: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """One bound update from the global ≤-mid counts. The tie rule ("if enough
    samples are ≤ mid, the answer is ≤ mid") lives ONLY here — shared by the
    in-device loop, the sharded build, and the host-streamed loop."""
    go_low = le >= rank + 1
    return jnp.where(go_low, low, mid + 1), jnp.where(go_low, mid, high)


def bisect_loop(
    bits: jax.Array,
    mask: jax.Array,
    rank: jax.Array,
    count_reduce: Callable[[jax.Array], jax.Array] = lambda le: le,
    num_iters: int = 31,
) -> jax.Array:
    """The shared bisection core: binary search over the 31-bit pattern space.

    ``count_reduce`` folds per-shard counts into global counts — identity on a
    single device, an exact integer ``psum`` along the mesh's time axis in the
    sharded build (`krr_tpu.parallel.fleet`). All callers therefore share
    every subtle semantic (rank formula, clamps, tie handling) by construction.
    """
    lo, hi = bisect_bounds(bits.shape[0])

    def body(_, carry):
        low, high = carry
        mid = bisect_mid(low, high)
        le_local = jnp.sum(jnp.where(mask & (bits <= mid[:, None]), 1, 0), axis=1, dtype=jnp.int32)
        return bisect_update(low, high, mid, count_reduce(le_local), rank)

    low, _ = jax.lax.fori_loop(0, num_iters, body, (lo, hi))
    return jax.lax.bitcast_convert_type(low, jnp.float32)


@partial(jax.jit, static_argnames=("num_iters",))
def masked_percentile_bisect(
    values: jax.Array,
    counts: jax.Array,
    q: jax.Array | float,
    num_iters: int = 31,
) -> jax.Array:
    """Per-row exact percentile (reference rank semantics: sorted index
    ``floor((n-1) * q / 100)``) of non-negative float32 data via bit bisection.

    NaN for empty rows. Requires values ≥ 0 (true for CPU seconds and byte
    counts; enforced by clamping).
    """
    n, t = values.shape
    mask = jnp.arange(t, dtype=jnp.int32)[None, :] < counts[:, None]
    result = bisect_loop(as_ordered_bits(values), mask, selection_rank(counts, q), num_iters=num_iters)
    return jnp.where(counts > 0, result, jnp.nan)


def masked_percentile_bisect_from_host(
    values: "object",
    counts: "object",
    q: float,
    chunk_size: int = 8192,
    num_iters: int = 31,
    sharding=None,
) -> "object":
    """Exact percentile of a **host-resident** ``[N, T]`` matrix that doesn't
    fit in device memory: the same bit-space bisection, with each iteration's
    counting pass streamed over host chunks (`stream_host_chunks`).

    Selects the exact same sample as :func:`masked_percentile_bisect` for any
    ``q`` — the escape hatch for mid-range percentiles, where no bounded exact
    sketch exists. Host→device traffic is ``num_iters ×`` the matrix, so when
    the rank-from-the-top fits a top-K sketch (q ≳ 97 at reference sample
    rates), prefer the one-pass `krr_tpu.ops.topk_sketch.build_from_host`.
    Returns a host float32 array; NaN for empty rows.
    """
    import numpy as np

    from krr_tpu.ops.chunked import HostChunkStreamer

    n = values.shape[0]
    counts32 = np.asarray(counts, dtype=np.int32)
    rank = selection_rank(jnp.asarray(counts32), q)
    lo, hi = bisect_bounds(n)
    streamer = HostChunkStreamer(values, counts32, chunk_size, sharding=sharding)

    def count_le(carry, chunk, valid):
        mid, le = carry
        le_chunk = jnp.sum(
            jnp.where(valid & (as_ordered_bits(chunk) <= mid[:, None]), 1, 0),
            axis=1,
            dtype=jnp.int32,
        )
        return mid, le + le_chunk

    for _ in range(num_iters):
        mid = bisect_mid(lo, hi)
        _, le = streamer.run((mid, jnp.zeros((n,), dtype=jnp.int32)), count_le)
        lo, hi = bisect_update(lo, hi, mid, le, rank)

    result = np.asarray(jax.lax.bitcast_convert_type(lo, jnp.float32))
    return np.where(counts32 > 0, result, np.nan)
