"""Pallas TPU kernels: fused bit-space bisection selection + row max.

The jnp bisection (`krr_tpu.ops.selection`) launches 31 counting passes, each
re-reading the full ``[N, T]`` matrix from HBM — correct, but 31× the memory
traffic of the theoretical minimum. Each row's selection is *independent*, so
the selection kernel tiles rows, DMAs a row-tile's **entire** time extent into
VMEM once, and runs all 31 bisection iterations in-kernel against the resident
tile — including the float→ordered-bits conversion, so raw float32 values are
read from HBM exactly once.

Two in-kernel layout tricks matter on the VPU (measured on v5e at the
BASELINE.md headline shape, 10k × 120,960):

* **Premasked sentinel bits.** Invalid positions are folded into the ordered
  bit space *once* (``INT32_MAX`` sorts above every finite sample) so the
  bisection loop is a bare compare+accumulate — 2 ops/element/iteration
  instead of 4 (mask AND, compare, select, accumulate). ~1.4× on the loop.
* **Lane-folded reductions.** A row-wise reduce along the minor (lane) axis is
  a cross-lane operation the VPU does poorly. Reshaping the tile to
  ``[rows, T/128, 128]`` and reducing the *middle* axis turns almost the whole
  reduction into element-wise vector-register ops, leaving one final 128-wide
  cross-lane pass per row. ~1.5× on the loop, ~3× on the row max.

``fleet_exact`` fuses the whole exact `simple`-strategy device program — CPU
percentile selection + memory peak — into ONE dispatch returning ONE stacked
array, because on a tunneled TPU backend each dispatch+readback round trip
costs tens of milliseconds: one call, one readback. Together with the kernel
tricks this took the headline bench from ~35k to ~75k containers/s.

Shapes: the row-tile's time extent must fit VMEM three times over (input
double-buffering + the premasked-bits temporary): ROW_TILE × T × 4 B × 3 ≤
~12 MB handles T up to ~131k — over 7 days @ 5 s. Larger T, non-TPU backends
(tests use interpret mode), and degenerate shapes fall back to the jnp path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROW_TILE = 8
LANE = 128
#: Ordered-bit sentinel for invalid positions: sorts above every finite
#: non-negative float's bit pattern (it is the NaN pattern 0x7fffffff).
INT32_MAX = 2**31 - 1
#: VMEM budget for one row-tile's working set (bytes); beyond this fall back
#: to jnp. Working set ≈ 3 tiles: double-buffered input + premasked bits.
VMEM_TILE_BUDGET = 12 * 1024 * 1024


def _fold(tile: jax.Array) -> jax.Array:
    """[rows, T] → [rows, T/128, 128] so reductions ride element-wise vregs."""
    rows, t = tile.shape
    return tile.reshape(rows, t // LANE, LANE)


def _bisect_kernel(values_ref, meta_ref, out_ref, *, num_iters: int):
    rows, t = values_ref.shape
    counts = meta_ref[:, :1]
    rank = meta_ref[:, 1:2]
    position = jax.lax.broadcasted_iota(jnp.int32, (rows, t), 1)
    # Float→value-monotone int bits with invalid positions premasked to the
    # top of the order, computed in VMEM: HBM serves the raw float32 tile once.
    bits = _fold(
        jnp.where(
            position < counts,
            pltpu.bitcast(jnp.maximum(values_ref[:], 0.0), jnp.int32),
            jnp.int32(INT32_MAX),
        )
    )

    lo = jnp.zeros((rows, LANE), dtype=jnp.int32)
    hi = jnp.full((rows, LANE), jnp.int32(INT32_MAX), dtype=jnp.int32)

    def body(_, carry):
        low, high = carry
        mid = low + (high - low) // 2
        cmp = (bits <= mid[:, :1].reshape(rows, 1, 1)).astype(jnp.int32)
        le = jnp.sum(jnp.sum(cmp, axis=1), axis=1, keepdims=True)
        # If enough samples are <= mid, the answer is <= mid. Sentinel rows
        # (count 0) converge to INT32_MAX whose float bit pattern is NaN.
        go_low = le >= rank + 1
        return jnp.where(go_low, low, mid + 1), jnp.where(go_low, mid, high)

    low, _ = jax.lax.fori_loop(0, num_iters, body, (lo, hi))
    out_ref[:] = pltpu.bitcast(jnp.broadcast_to(low[:, :1], (rows, LANE)), jnp.float32)


def _rowmax_kernel(values_ref, counts_ref, out_ref):
    rows, t = values_ref.shape
    position = jax.lax.broadcasted_iota(jnp.int32, (rows, t), 1)
    masked = _fold(jnp.where(position < counts_ref[:, :1], values_ref[:], -jnp.inf))
    folded = jnp.max(masked, axis=1)  # element-wise vreg maxes
    out_ref[:] = jnp.broadcast_to(jnp.max(folded, axis=1, keepdims=True), (rows, LANE))


def supports(t: int) -> bool:
    """Whether one row-tile's working set fits the VMEM budget."""
    return 0 < 3 * ROW_TILE * t * 4 <= VMEM_TILE_BUDGET


def _pad_inputs(values: jax.Array, counts: jax.Array):
    """Pad rows to ROW_TILE and T to LANE; padding never enters any result:
    padded rows carry count 0 and padded columns sit past every row's count,
    so the in-kernel validity premask excludes them regardless of value."""
    n, t = values.shape
    pad_rows = (-n) % ROW_TILE
    pad_t = (-t) % LANE
    if pad_rows or pad_t:
        values = jnp.pad(values, ((0, pad_rows), (0, pad_t)))
    return values, jnp.pad(counts.astype(jnp.int32), (0, pad_rows))


def _row_meta(counts: jax.Array, rank: jax.Array) -> jax.Array:
    """Per-row scalars ride as one [N, LANE] block: col 0 count, col 1 rank."""
    meta = jnp.concatenate([counts[:, None], rank[:, None]], axis=1)
    return jnp.pad(meta, ((0, 0), (0, LANE - 2)))


def _tile_specs(t: int):
    return [
        pl.BlockSpec((ROW_TILE, t), lambda i: (i, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((ROW_TILE, LANE), lambda i: (i, 0), memory_space=pltpu.VMEM),
    ]


_OUT_SPEC = pl.BlockSpec((ROW_TILE, LANE), lambda i: (i, 0), memory_space=pltpu.VMEM)


def _select_device(values: jax.Array, counts: jax.Array, q, num_iters: int, interpret: bool):
    """Padded-and-masked selection pallas_call; returns per-row [N] floats."""
    from krr_tpu.ops.selection import selection_rank

    n = values.shape[0]
    values, counts_p = _pad_inputs(values, counts)
    np_, tp = values.shape
    out = pl.pallas_call(
        functools.partial(_bisect_kernel, num_iters=num_iters),
        grid=(np_ // ROW_TILE,),
        in_specs=_tile_specs(tp),
        out_specs=_OUT_SPEC,
        out_shape=jax.ShapeDtypeStruct((np_, LANE), jnp.float32),
        interpret=interpret,
    )(values, _row_meta(counts_p, selection_rank(counts_p, q)))
    return jnp.where(counts > 0, out[:n, 0], jnp.nan)


def _rowmax_device(values: jax.Array, counts: jax.Array, interpret: bool):
    n = values.shape[0]
    values, counts_p = _pad_inputs(values, counts)
    np_, tp = values.shape
    out = pl.pallas_call(
        _rowmax_kernel,
        grid=(np_ // ROW_TILE,),
        in_specs=_tile_specs(tp),
        out_specs=_OUT_SPEC,
        out_shape=jax.ShapeDtypeStruct((np_, LANE), jnp.float32),
        interpret=interpret,
    )(values, jnp.broadcast_to(counts_p[:, None], (np_, LANE)))
    return jnp.where(counts > 0, out[:n, 0], jnp.nan)


@functools.partial(jax.jit, static_argnames=("num_iters", "interpret"))
def _pallas_bisect(values, counts, q, num_iters: int, interpret: bool):
    return _select_device(values, counts, q, num_iters, interpret)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pallas_rowmax(values, counts, interpret: bool):
    return _rowmax_device(values, counts, interpret)


@functools.partial(jax.jit, static_argnames=("num_iters", "interpret"))
def _fleet_exact(cpu_values, cpu_counts, mem_values, mem_counts, q, num_iters: int, interpret: bool):
    return jnp.stack(
        [
            _select_device(cpu_values, cpu_counts, q, num_iters, interpret),
            _rowmax_device(mem_values, mem_counts, interpret),
        ]
    )


@functools.partial(jax.jit, static_argnames=("num_iters",))
def _fleet_exact_jnp(cpu_values, cpu_counts, mem_values, mem_counts, q, num_iters: int):
    """Module-level jitted jnp fallback (cache persists across batches)."""
    from krr_tpu.ops.quantile import masked_max
    from krr_tpu.ops.selection import masked_percentile_bisect

    return jnp.stack(
        [
            masked_percentile_bisect(cpu_values, cpu_counts, q, num_iters=num_iters),
            masked_max(mem_values, mem_counts),
        ]
    )


def _use_pallas(t: int, interpret: bool) -> bool:
    return supports(t) and (interpret or jax.default_backend() == "tpu")


def masked_percentile_bisect_pallas(
    values: jax.Array,
    counts: jax.Array,
    q: float,
    num_iters: int = 31,
    interpret: bool = False,
) -> jax.Array:
    """Drop-in (bit-identical) replacement for
    ``selection.masked_percentile_bisect`` backed by the fused kernel; falls
    back to the jnp path when the tile doesn't fit VMEM or no TPU is present."""
    from krr_tpu.ops.selection import masked_percentile_bisect

    n, t = values.shape
    if n == 0 or t == 0:
        return jnp.full((n,), jnp.nan, dtype=jnp.float32)
    if not _use_pallas(t, interpret):
        return masked_percentile_bisect(values, counts, q, num_iters=num_iters)
    return _pallas_bisect(values, counts, jnp.float32(q), num_iters, interpret)


def masked_max_pallas(values: jax.Array, counts: jax.Array, interpret: bool = False) -> jax.Array:
    """Drop-in (bit-identical) replacement for ``quantile.masked_max`` backed
    by the lane-folded row-max kernel; same fallback rules as the selection."""
    from krr_tpu.ops.quantile import masked_max

    n, t = values.shape
    if n == 0 or t == 0:
        return jnp.full((n,), jnp.nan, dtype=jnp.float32)
    if not _use_pallas(t, interpret):
        return masked_max(values, counts)
    return _pallas_rowmax(values, counts, interpret)


def fleet_exact(
    cpu_values: jax.Array,
    cpu_counts: jax.Array,
    mem_values: jax.Array,
    mem_counts: jax.Array,
    q: float,
    num_iters: int = 31,
    interpret: bool = False,
) -> jax.Array:
    """The exact `simple`-strategy device program in ONE dispatch.

    Returns a stacked ``[2, N]`` float32 array — row 0 the per-container CPU
    percentile (reference rank semantics, NaN for empty rows), row 1 the
    memory peak — so the host needs exactly one readback. CPU and memory
    histories may have different time extents. Falls back to the jnp ops off
    TPU (still one fused XLA program)."""
    n, tc = cpu_values.shape
    tm = mem_values.shape[1]
    if n == 0:
        return jnp.zeros((2, 0), dtype=jnp.float32)
    if tc == 0 or tm == 0:
        nan_row = jnp.full((n,), jnp.nan, jnp.float32)
        p99 = masked_percentile_bisect_pallas(cpu_values, cpu_counts, q, num_iters, interpret) if tc else nan_row
        peak = masked_max_pallas(mem_values, mem_counts, interpret) if tm else nan_row
        return jnp.stack([p99, peak])
    if not (_use_pallas(tc, interpret) and _use_pallas(tm, interpret)):
        return _fleet_exact_jnp(
            cpu_values, cpu_counts, mem_values, mem_counts, jnp.float32(q), num_iters
        )
    return _fleet_exact(
        cpu_values, cpu_counts, mem_values, mem_counts, jnp.float32(q), num_iters, interpret
    )
