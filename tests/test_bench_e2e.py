"""Smoke-test the end-to-end benchmark harness at tiny scale.

`bench.py` folds `bench_e2e.py`'s numbers into its JSON via a subprocess and
degrades to a note on failure — so without this test, a broken e2e harness
would silently drop the end-to-end metrics from every recorded round.
"""

import bench_e2e


def test_run_e2e_small():
    out = bench_e2e.run_e2e(n_containers=6, samples=48)
    assert out["e2e_containers"] == 6
    assert out["e2e_objects_per_sec"] > 0
    assert out["e2e_objects_per_sec_cold"] > 0
    assert out["fetch_seconds"] > 0 and out["compute_seconds"] > 0


def test_run_digest_ingest_small():
    out = bench_e2e.run_digest_ingest(64)
    assert out["digest_ingest_100k_objects_per_sec"] > 0


def test_run_fleet_e2e_small():
    """The full-fleet scan leg at tiny scale, shared-series fixture included
    (pods beyond `shared` serve aliased histories)."""
    out = bench_e2e.run_fleet_e2e(n_containers=24, samples=48, shared=8)
    assert out["fleet_e2e_containers"] == 24
    assert out["fleet_e2e_objects_per_sec"] > 0
    assert out["fleet_e2e_fetch_seconds"] > 0
