"""Benchmark: containers right-sized per second on the available accelerator.

Measures the full fleet recommendation step at the BASELINE.md headline
*workload shape* (10k containers × 7 days of 5-second samples = 120,960
timesteps/container, the config-3 scale) using the production
``simple``-strategy device program: ``fleet_exact`` — **exact** fused-Pallas
bit-space bisection selection over the CPU histories + lane-folded row max
over the memory histories, one dispatch, one readback
(`krr_tpu.ops.pallas_select`). Note this is a stronger result than
BASELINE.md's config-3 row asks for (that row names the approximate tdigest
sketch): the exact kernel turned out faster than the sketch for HBM-resident
data, so the headline metric was renamed from
``containers_per_sec_tdigest_7d_at_5s`` (recorded through 2026-07-29) to
``containers_per_sec_exact_p99_7d_at_5s``. The ``tdigest`` sketch path —
still the right tool for streamed/multi-source/incremental data — is timed as
a secondary number on stderr.

Baseline: the reference's algorithm (pure-Python Decimal flatten/sort/index,
`/root/reference/robusta_krr/strategies/simple.py:24-36`) timed on a small
sample and extrapolated per container.

Data is generated on-device in chunks (the bench isolates kernel throughput
from Prometheus-side fetch, which is network-bound). NOTE: on the tunneled
TPU backend ``block_until_ready`` returns early — sync is via small host
readbacks. Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "containers/s", "vs_baseline": N}

Env knobs: BENCH_CONTAINERS (default 10000), BENCH_TIMESTEPS (default 120960),
BENCH_CHUNK (default 8192), BENCH_PY_SAMPLE (default 3), BENCH_SKIP_DIGEST.
"""

from __future__ import annotations

import json
import os
import sys
import time
from decimal import Decimal


def python_reference_seconds_per_container(timesteps: int, sample: int) -> float:
    """Time the reference algorithm (Decimal flatten → percentile-index → max;
    sorted, per its documented intent) on `sample` containers."""
    import numpy as np

    rng = np.random.default_rng(7)
    histories = []
    for _ in range(sample):
        cpu = [Decimal(repr(float(v))) for v in rng.gamma(2.0, 0.05, size=timesteps)]
        mem = [Decimal(repr(float(v))) for v in rng.uniform(1e7, 4e8, size=timesteps)]
        histories.append((cpu, mem))

    start = time.perf_counter()
    for cpu, mem in histories:
        data = sorted(cpu)
        _ = data[int((len(data) - 1) * Decimal(99) / 100)]
        _ = max(mem) * Decimal("1.05")
    return (time.perf_counter() - start) / sample


def main() -> None:
    # Shapes are aligned down to the kernel tile boundaries (8 rows, 128
    # lanes) so `fleet_exact` takes its zero-copy path: at ~10 GB of resident
    # history there is no HBM headroom for `_pad_inputs` to make padded
    # copies of both arrays. The defaults are already aligned.
    n = max(8, int(os.environ.get("BENCH_CONTAINERS", 10_000)) // 8 * 8)
    t = max(128, int(os.environ.get("BENCH_TIMESTEPS", 120_960)) // 128 * 128)
    chunk = int(os.environ.get("BENCH_CHUNK", 8_192))
    py_sample = int(os.environ.get("BENCH_PY_SAMPLE", 3))

    import jax
    import jax.numpy as jnp
    import numpy as np

    from krr_tpu.ops import digest as digest_ops
    from krr_tpu.ops.digest import DigestSpec
    from krr_tpu.ops.pallas_select import fleet_exact
    from krr_tpu.ops.quantile import masked_max

    device = jax.devices()[0]
    print(f"bench: {n} containers x {t} timesteps on {device.platform}:{device.device_kind}", file=sys.stderr)

    # On-device data generation, chunked so RNG temp buffers stay small (a
    # one-shot gamma at [10k x 120k] OOMs on threefry temps alone). Arrays are
    # born at exactly [n, t], already tile-aligned (see main), so the fused
    # kernel never pads; any trailing partial chunk is generated as one extra
    # block.
    chunk = min(chunk, t)
    num_chunks = t // chunk
    remainder = t % chunk

    @jax.jit
    def generate(key):
        def cpu_like(block):
            return block * block * 0.8 + 1e-4  # right-skewed cpu-like values

        def body(i, buf):
            sub = jax.random.fold_in(key, i)
            block = cpu_like(jax.random.uniform(sub, (n, chunk), dtype=jnp.float32))
            return jax.lax.dynamic_update_slice(buf, block, (0, i * chunk))

        buf = jax.lax.fori_loop(0, num_chunks, body, jnp.zeros((n, t), jnp.float32))
        if remainder:
            tail = cpu_like(
                jax.random.uniform(jax.random.fold_in(key, num_chunks), (n, remainder), jnp.float32)
            )
            buf = jax.lax.dynamic_update_slice(buf, tail, (0, num_chunks * chunk))
        return buf

    values = generate(jax.random.PRNGKey(0))  # CPU histories
    mem_values = generate(jax.random.PRNGKey(1))  # memory histories (same shape)
    counts = jnp.full((n,), t, dtype=jnp.int32)
    _ = np.asarray(values[:1, :4])  # force generation
    _ = np.asarray(mem_values[:1, :4])

    def exact_step(values, counts):
        # The full exact strategy program — CPU p99 selection + memory peak —
        # in ONE dispatch with ONE readback (Pallas kernels on TPU, jnp
        # elsewhere; bit-identical). Round trips dominate at this speed.
        return fleet_exact(values, counts, mem_values, counts, 99.0)

    def timed(step) -> float:
        _ = np.asarray(step(values, counts))  # warmup/compile
        best = float("inf")
        for _i in range(3):
            start = time.perf_counter()
            _ = np.asarray(step(values, counts))
            best = min(best, time.perf_counter() - start)
        return best

    exact_elapsed = timed(exact_step)
    throughput = n / exact_elapsed
    print(f"bench: exact bisect+max {exact_elapsed:.3f}s -> {throughput:.0f} containers/s", file=sys.stderr)

    # Free the memory-history array before the sketch paths: both resident
    # plus sketch-build temporaries exceed a single chip's HBM.
    del exact_step
    mem_values = None

    if not os.environ.get("BENCH_SKIP_DIGEST"):
        from krr_tpu.ops import topk_sketch as topk_ops

        k = topk_ops.required_k(t, 99.0)

        @jax.jit
        def topk_step(values, counts):
            sketch = topk_ops.build_from_packed(values, counts, k=k, chunk_size=chunk)
            return topk_ops.percentile(sketch, 99.0), masked_max(values, counts)

        topk_elapsed = timed(topk_step)
        print(
            f"bench: exact topk sketch (K={k}) {topk_elapsed:.3f}s -> {n / topk_elapsed:.0f} containers/s "
            f"(streaming/mergeable path, zero error — tdigest default for p99)",
            file=sys.stderr,
        )

        spec = DigestSpec(gamma=1.01, min_value=1e-7, num_buckets=2560)

        @jax.jit
        def digest_step(values, counts):
            d = digest_ops.build_from_packed(spec, values, counts, chunk_size=chunk)
            return digest_ops.percentile(spec, d, 99.0), digest_ops.peak(d)

        digest_elapsed = timed(digest_step)
        print(
            f"bench: tdigest sketch {digest_elapsed:.3f}s -> {n / digest_elapsed:.0f} containers/s "
            f"(streaming/mergeable path)",
            file=sys.stderr,
        )

    py_per_container = python_reference_seconds_per_container(t, py_sample)
    baseline_throughput = 1.0 / py_per_container
    print(
        f"bench: python-reference {py_per_container:.3f}s/container ({baseline_throughput:.2f}/s)",
        file=sys.stderr,
    )

    print(
        json.dumps(
            {
                "metric": "containers_per_sec_exact_p99_7d_at_5s",
                "value": round(throughput, 1),
                "unit": "containers/s",
                "vs_baseline": round(throughput / baseline_throughput, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
