"""Resident serve state: the digest store + the published result snapshot.

The cache is a READ/WRITE-locked published snapshot: HTTP handlers take the
read side for the few microseconds it takes to grab the current
:class:`Snapshot` reference, and the scheduler takes the write side only for
the atomic swap at the END of a scan — so queries keep serving the previous
result for the whole duration of an in-flight scan (fetch, fold, compute all
happen outside the lock, on a private window that only touches the store
once complete). The digest store itself is owned by the scheduler (one scan
in flight at a time, serialized by ``scan_lock``); readers never touch it.
"""

from __future__ import annotations

import asyncio
import contextlib
import time
from dataclasses import dataclass
from typing import TYPE_CHECKING, Optional

from krr_tpu.server.metrics import MetricsRegistry

if TYPE_CHECKING:
    from krr_tpu.core.streaming import DigestStore
    from krr_tpu.history.journal import RecommendationJournal
    from krr_tpu.models.result import Result
    from krr_tpu.obs.health import SloEngine


class ReadWriteLock:
    """Asyncio readers-writer lock: any number of concurrent readers, one
    exclusive writer; a waiting writer blocks new readers (no writer
    starvation under a steady query stream)."""

    def __init__(self) -> None:
        self._cond = asyncio.Condition()
        self._readers = 0
        self._writers_waiting = 0
        self._writing = False

    @contextlib.asynccontextmanager
    async def read(self):
        async with self._cond:
            while self._writing or self._writers_waiting:
                await self._cond.wait()
            self._readers += 1
        try:
            yield
        finally:
            async with self._cond:
                self._readers -= 1
                if self._readers == 0:
                    self._cond.notify_all()

    @contextlib.asynccontextmanager
    async def write(self):
        async with self._cond:
            self._writers_waiting += 1
            try:
                while self._writing or self._readers:
                    await self._cond.wait()
            finally:
                self._writers_waiting -= 1
            self._writing = True
        try:
            yield
        finally:
            async with self._cond:
                self._writing = False
                self._cond.notify_all()


@dataclass(frozen=True)
class Snapshot:
    """One published scan: everything a query needs, immutable by contract.

    ``body_json`` is the whole-fleet JSON rendered AND encoded once at
    publish time (via the machine formatter) — the hot unfiltered response
    is a byte copy, not a per-request model dump or UTF-8 encode (multi-MB
    at fleet scale, and the handler runs on the event loop).
    """

    result: "Result"
    body_json: bytes
    window_end: float  # unix ts of the scan window's right edge
    published_at: float


class ServerState:
    """The serve process's shared mutable state."""

    def __init__(
        self,
        store: "DigestStore",
        journal: "Optional[RecommendationJournal]" = None,
        metrics: Optional[MetricsRegistry] = None,
    ) -> None:
        self.store = store
        #: The recommendation flight recorder (`krr_tpu.history.journal`):
        #: every scheduler recompute appends here; GET /history and
        #: GET /drift read it from worker threads (the journal carries its
        #: own lock). None only for states built without a server.
        self.journal = journal
        #: One scan in flight at a time (scheduler ticks + any manual kicks).
        self.scan_lock = asyncio.Lock()
        self.rwlock = ReadWriteLock()
        #: Injectable so the serve composition root can hand in the scan
        #: session's registry — per-query Prometheus telemetry then lands on
        #: the same /metrics exposition as the scheduler's scan telemetry.
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.started_at = time.time()
        #: Right edge of the last FOLDED window — the next delta starts one
        #: step after it. Advanced only after a fold completes, so a
        #: cancelled scan refetches its window instead of losing it.
        self.last_end: Optional[float] = None
        #: The last publish's hysteresis outcome (None before any publish):
        #: how many workloads' out-of-band changes were withheld, and how
        #: many published values moved — surfaced on /healthz so operators
        #: can tell a quiet fleet from a stuck gate.
        self.last_publish_suppressed: Optional[int] = None
        self.last_publish_changed: Optional[int] = None
        #: Trace id of the last completed scan tick — the join key between
        #: /healthz, structured log lines, and /debug/trace spans.
        self.last_scan_id: Optional[str] = None
        #: Quarantined workloads (degraded ticks): object key → unix time of
        #: the last window actually folded for it. Their published
        #: recommendations carry forward last-good digests; /recommendations
        #: marks each scan with this timestamp (``stale_since``), /healthz
        #: and ``krr_tpu_stale_workloads`` count them. Owned by the
        #: scheduler; handlers only read.
        self.stale_workloads: dict[str, float] = {}
        #: Consecutive failed (aborted) scheduler ticks — 0 while healthy;
        #: visible on /healthz and /statusz so degraded state doesn't
        #: require grepping logs.
        self.consecutive_scan_failures: int = 0
        #: The most recent scan abort's error (survives recovery as a
        #: post-mortem breadcrumb; consecutive_scan_failures == 0 says
        #: whether it is current).
        self.last_scan_error: Optional[str] = None
        #: The SLO engine (`krr_tpu.obs.health`): the scheduler evaluates it
        #: per tick, GET /statusz renders it, /healthz downgrades to
        #: ``degraded`` while it has firing alerts. None for states built
        #: without a server (unit tests, embedders).
        self.slo: "Optional[SloEngine]" = None
        #: The scan flight recorder (`krr_tpu.obs.timeline`): the scheduler
        #: appends one record per completed tick, GET /debug/timeline and
        #: the SIGUSR2 trend artifact read it. None for states built
        #: without a server.
        self.timeline = None
        #: The regression sentinel (`krr_tpu.obs.sentinel`): classifies each
        #: timeline record against rolling baselines; /statusz renders its
        #: trend section. None when --no-sentinel (or no server).
        self.sentinel = None
        #: Persistence posture (durable store saves): True while the last
        #: persist attempt failed (ENOSPC/EIO) — serve keeps publishing
        #: from memory, /healthz downgrades to ``degraded``, and the next
        #: tick retries with the backlog. Owned by the scheduler.
        self.persist_failing: bool = False
        #: Cumulative failed persist attempts this process (the in-process
        #: twin of ``krr_tpu_persist_failures_total``).
        self.persist_failures: int = 0
        #: The most recent persist failure's error (survives recovery as a
        #: breadcrumb; ``persist_failing`` says whether it is current).
        self.last_persist_error: Optional[str] = None
        #: Clusters whose last discovery listing FAILED (fail-soft degraded
        #: to an empty cluster): cluster → error string. Surfaced on
        #: /healthz and /statusz so a silently smaller fleet is visible;
        #: the loader counts them in
        #: ``krr_tpu_discovery_cluster_failures_total``. Owned by the
        #: scheduler's discovery leg.
        self.discovery_failed_clusters: dict[str, str] = {}
        #: The federation aggregator (`krr_tpu.federation.aggregator`) when
        #: serve runs with ``--federation-listen``: /healthz and /statusz
        #: render its per-shard connected/epoch/lag state. None otherwise.
        self.federation = None
        self._snapshot: Optional[Snapshot] = None

    async def publish(self, snapshot: Snapshot) -> None:
        async with self.rwlock.write():
            self._snapshot = snapshot

    async def snapshot(self) -> Optional[Snapshot]:
        async with self.rwlock.read():
            return self._snapshot

    def peek(self) -> Optional[Snapshot]:
        """Lock-free read for logging/tests (reference reads are atomic)."""
        return self._snapshot
