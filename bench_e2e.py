"""End-to-end scale benchmark: the real Runner against in-process fakes.

`bench.py` isolates device-kernel throughput; this script measures the whole
production pipeline — discover (apiserver list + pod resolution) → bulk
Prometheus fan-out → native parse → ragged pack → device compute → severity
— by driving the actual `Runner` against the hermetic fake apiserver +
Prometheus from `tests/fakes/servers.py` at fleet scale, plus the
digest-ingest compute path at a synthetic 100k-container fleet (the
BASELINE.md config-4 fleet size; raw fetch at that scale is bounded by the
Prometheus side, which a local fake can't represent — see README).

The fakes run in a SEPARATE process (spawned, not forked — forking after JAX
initializes is unsafe), so the server's GIL never blocks the scanner's, and
batched response bodies are pre-rendered server-side on the first (cold)
scan and served from cache on the warm scan that produces the headline
number. CAVEATS of this rig: (a) ONE CPU core (`nproc` = 1), so the measured
wall-clock is the SUM of server serving + client read + parse + routing +
pack, not their overlap; (b) the tunneled TPU transfers host→device at
~12 MB/s (measured), so the raw path's compute_seconds at fleet scale is
mostly input transfer — production PCIe moves GB/s. Solo component
throughputs (the honest per-core numbers): native parse ~450 MB/s,
http.client read ~1.1 GB/s (see BASELINE.md's ingest budget). The
digest-ingest path ships no bulk arrays to the device at all, which is why
its e2e number is several times the raw path's here.

Digest-ingest scans run the STREAMED scan pipeline by default
(`krr_tpu.core.pipeline`: discovery, fetch, and fold overlapped through a
bounded queue); the fleet leg also times a ``pipeline_depth=0`` staged
control at the same warm caches and records the streamed/staged ratio plus
the measured stage overlap (``fleet_e2e_overlap_pct``). Streamed scans fuse
discovery CPU into the fetch leg, so ``*_discover_cpu_seconds`` reads 0 for
them — the discover WALL span is still reported from inside the pipeline.

Prints ONE JSON line:
    {"e2e_objects_per_sec": N, "e2e_objects_per_sec_cold": N,
     "e2e_containers": N, "discover_seconds": N, "fetch_seconds": N,
     "compute_seconds": N, "e2e_digest_objects_per_sec": N,
     "e2e_digest_fetch_seconds": N, "e2e_digest_overlap_pct": N,
     "digest_ingest_100k_objects_per_sec": N,
     "fleet_e2e_*": ...,     # ONE FULL 100k-container scan with phase
                             # breakdown + staged control + overlap pct
     "digest_store_*": ...,  # 100k x 2560 store merge/query/save/load + MB
     "ingest_*": ...}        # scanner sink throughputs + bytes/sample

Env knobs: BENCH_E2E_CONTAINERS (default 1000; bench.py's subprocess sets
10000), BENCH_E2E_SAMPLES (default 1344 = 2 weeks @ 15 min, the reference's
workload shape), BENCH_E2E_INGEST_ROWS (default 100000; 0 skips),
BENCH_E2E_FLEET_ROWS (default 100000; 0 skips the full-fleet scan leg),
BENCH_E2E_FLEET_ONLY (run ONLY the full-fleet scan leg and exit — bench.py
uses this to isolate the ~15-minute leg in its own subprocess),
BENCH_E2E_STORE_ROWS (default 100000; 0 skips the DigestStore leg).
"""

from __future__ import annotations

import asyncio
import contextlib
import io
import json
import os
import sys
import tempfile
import time


def _serve_fixture(n_containers: int, samples: int, conn, shared: int = 0) -> None:
    """Child-process entry: build the fixture, serve it, report the port,
    hold until the parent is done. Runs under multiprocessing 'spawn', so
    this must stay importable without side effects.

    ``shared > 0``: only the first ``shared`` pods get independently
    generated (and rendered) series; the rest serve one of those by
    reference (`FakeMetrics.alias_series`). 100k unique series would cost
    ~13 GB of rendered strings and minutes of formatting — identical
    histories across pods don't change what the scanner has to do."""
    import numpy as np

    from tests.fakes.servers import FakeBackend, FakeCluster, FakeMetrics, ServerThread

    cluster = FakeCluster()
    metrics = FakeMetrics()
    # Range-accurate serving: split-window fetches (the raw route's bounded
    # response windows) must receive exactly their slice — serving the full
    # series per window would multiply the measured transfer by the window
    # count. The scan pins its end (scan_end, below) onto this grid.
    metrics.enforce_range = True
    rng = np.random.default_rng(5)
    pods = []
    for i in range(n_containers):
        name = f"wl-{i}"
        (pod,) = cluster.add_workload_with_pods("Deployment", name, "default", pod_count=1)
        pods.append(pod)
        if shared and i >= shared:
            metrics.alias_series("default", "main", pod, pods[i % shared])
        else:
            # Realistic value precision (irates ~0.1 millicore resolution,
            # working sets page-granular): full-precision iid random
            # mantissas would make the rendered JSON artificially
            # incompressible and the compressed-transport leg would
            # benchmark the RNG's entropy, not the wire. Body shape
            # (samples, labels, timestamps) is unchanged.
            metrics.set_series(
                "default",
                "main",
                pod,
                cpu=np.round(rng.gamma(2.0, 0.05, samples), 4),
                memory=np.floor(rng.uniform(5e7, 4e8, samples) / 4096) * 4096,
            )
    server = ServerThread(FakeBackend(cluster, metrics)).start()
    conn.send(server.port)
    conn.recv()  # parent signals completion
    server.stop()


def _proc_cpu_seconds(pid: int) -> float:
    """utime+stime of one process from /proc/<pid>/stat — the fake server's
    CPU share of a scan, read from the parent (the child stays untouched)."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            fields = f.read().rsplit(b")", 1)[1].split()
        return (int(fields[11]) + int(fields[12])) / os.sysconf("SC_CLK_TCK")
    except (OSError, IndexError, ValueError):
        return float("nan")


@contextlib.contextmanager
def _fixture_env(n_containers: int, samples: int, shared: int = 0):
    """Spawn the fake backend in a child process and yield
    ``(make_config, one_scan)`` — the shared scaffolding of every e2e leg.
    ``one_scan(config)`` runs one full Runner scan and returns
    ``(elapsed_seconds, runner.stats)``; the stats carry the fake server's
    CPU spend for that scan as ``server_cpu_seconds`` (client CPU legs come
    from the Runner's own process_time stats), so the measured wall can be
    attributed second-by-second between client work, server work, and
    genuine overlap/idle."""
    import multiprocessing

    import yaml

    from krr_tpu.core.config import Config
    from krr_tpu.core.runner import Runner

    ctx = multiprocessing.get_context("spawn")
    parent_conn, child_conn = ctx.Pipe()
    proc = ctx.Process(
        target=_serve_fixture, args=(n_containers, samples, child_conn, shared), daemon=True
    )
    proc.start()
    if not parent_conn.poll(timeout=600):
        proc.kill()
        raise RuntimeError("fake-server subprocess failed to start")
    try:
        port = parent_conn.recv()
    except EOFError:  # child died building the fixture — pipe EOF, not a port
        proc.kill()
        raise RuntimeError("fake-server subprocess died during fixture setup") from None
    server_url = f"http://127.0.0.1:{port}"
    try:
        with tempfile.TemporaryDirectory() as tmp:
            kubeconfig = os.path.join(tmp, "config")
            with open(kubeconfig, "w") as f:
                yaml.safe_dump(
                    {
                        "current-context": "fake",
                        "contexts": [{"name": "fake", "context": {"cluster": "fake", "user": "u"}}],
                        "clusters": [{"name": "fake", "cluster": {"server": server_url}}],
                        "users": [{"name": "u", "user": {"token": "t"}}],
                    },
                    f,
                )
            from krr_tpu.strategies.simple import SimpleStrategySettings
            from tests.fakes.servers import FakeBackend

            # Pin the window's right edge so the fake's range-anchored series
            # line up with the scan exactly, deriving the grid step from the
            # strategy the scan actually runs (15 min by default).
            step_seconds = SimpleStrategySettings().timeframe_timedelta.total_seconds()
            scan_end = FakeBackend.SERIES_ORIGIN + (samples - 1) * step_seconds

            def make_config(**overrides) -> Config:
                return Config(
                    kubeconfig=kubeconfig,
                    prometheus_url=server_url,
                    quiet=True,
                    format="json",
                    scan_end_timestamp=scan_end,
                    **overrides,
                )

            def one_scan(config) -> tuple[float, dict]:
                from krr_tpu.integrations.prometheus import TRANSPORT_PHASES

                runner = Runner(config)
                server_cpu = _proc_cpu_seconds(proc.pid)
                start = time.perf_counter()
                with contextlib.redirect_stdout(io.StringIO()):  # result JSON isn't the metric
                    asyncio.run(runner.run())
                elapsed = time.perf_counter() - start
                assert runner.stats["objects"] == n_containers, runner.stats
                runner.stats["server_cpu_seconds"] = _proc_cpu_seconds(proc.pid) - server_cpu
                # Transport-phase attribution of THIS scan's fetch leg, from
                # the runner's own registry (summed across every range
                # query; phases that never occurred read 0).
                for phase in TRANSPORT_PHASES:
                    runner.stats[f"prom_phase_{phase}_seconds"] = (
                        runner.metrics.value("krr_tpu_prom_phase_seconds_sum", phase=phase)
                        or 0.0
                    )
                runner.stats["prom_wire_bytes"] = runner.metrics.total(
                    "krr_tpu_prom_wire_bytes_total"
                )
                # Compressed-transport split: wire = what crossed the
                # socket (compressed when negotiated), decoded = the
                # post-inflate stream the scanner actually parsed.
                runner.stats["prom_decoded_bytes"] = runner.metrics.total(
                    "krr_tpu_prom_decoded_bytes_total"
                )
                runner.stats["prom_gzip_responses"] = (
                    runner.metrics.value(
                        "krr_tpu_prom_wire_encoding_total", encoding="gzip"
                    )
                    or 0.0
                )
                # Adaptive-fetch-plan engagement for the round record: how
                # many coalesced/sharded query groups the planner issued.
                for kind in ("coalesced", "sharded"):
                    runner.stats[f"fetch_plan_{kind}"] = (
                        runner.metrics.total(f"krr_tpu_fetch_plan_{kind}_total")
                    )
                return elapsed, runner.stats

            yield make_config, one_scan
    finally:
        try:
            parent_conn.send("done")
        except OSError:  # child already gone — don't mask the real failure
            pass
        proc.join(timeout=10)
        if proc.is_alive():
            proc.kill()


def run_e2e(n_containers: int, samples: int) -> dict:
    with _fixture_env(n_containers, samples) as (make_config, one_scan):
        config = make_config()
        # Cold scan pays one-time JIT compiles + the fake's body renders;
        # the warm scan is the steady-state a continuously-running
        # recommender sees.
        cold_elapsed, _cold = one_scan(config)
        elapsed, stats = one_scan(config)

        # The config-4 headline path end-to-end: tdigest digest-at-ingest
        # (responses fold into per-object digests inside the native
        # scanner; raw arrays never materialize). Same server, warm body
        # cache — directly comparable to the raw-path number above.
        digest_config = config.model_copy(
            update={"strategy": "tdigest", "other_args": {"digest_ingest": True}}
        )
        one_scan(digest_config)  # cold (digest-path JIT/compile)
        digest_elapsed, digest_stats = one_scan(digest_config)

        # PROXIED route at the same scale: the raw transport declines (as it
        # does under HTTP(S)_PROXY) and streamed ingest rides httpx
        # ``aiter_bytes`` into the same native sinks. Recording it here pins
        # the route's throughput-parity claim with a measured number
        # (round-4 verdict item 7) — same fixture, same warm body cache.
        from krr_tpu.integrations.prometheus import PrometheusLoader

        original_transport = PrometheusLoader.__dict__["_make_raw_transport"]
        PrometheusLoader._make_raw_transport = staticmethod(lambda url, headers, verify: None)
        try:
            one_scan(digest_config)  # warm the httpx route
            proxied_elapsed, proxied_stats = one_scan(digest_config)
        finally:
            # The descriptor itself (class __dict__), not the bare function —
            # re-assigning the unwrapped function would bind `self` as `url`
            # on instance access and silently break every later fetch.
            PrometheusLoader._make_raw_transport = original_transport

    return {
        "e2e_objects_per_sec": round(stats["objects"] / elapsed, 1),
        "e2e_objects_per_sec_cold": round(stats["objects"] / cold_elapsed, 1),
        "e2e_containers": int(stats["objects"]),
        "discover_seconds": round(stats["discover_seconds"], 3),
        "fetch_seconds": round(stats["fetch_seconds"], 3),
        "compute_seconds": round(stats["compute_seconds"], 3),
        "e2e_digest_objects_per_sec": round(digest_stats["objects"] / digest_elapsed, 1),
        "e2e_digest_fetch_seconds": round(digest_stats["fetch_seconds"], 3),
        "e2e_digest_overlap_pct": round(digest_stats.get("pipeline_overlap_pct", 0.0), 1),
        "e2e_digest_proxied_objects_per_sec": round(proxied_stats["objects"] / proxied_elapsed, 1),
        "e2e_digest_proxied_fetch_seconds": round(proxied_stats["fetch_seconds"], 3),
    }


def run_fleet_e2e(n_containers: int = 100_000, samples: int = 1344, shared: int = 512) -> dict:
    """One FULL config-4-width scan, measured, not extrapolated: 100k
    containers through discover → namespace-batched fetch → streamed native
    digest ingest → percentile → severity against the fake backend, window
    pinned via --scan-end-timestamp (round-3 verdict: the <60 s budget was
    an arithmetic case until someone ran the scan once). Digest-ingest route
    only — raw fetch at this width is bounded by the metrics backend, which
    a single-core local fake can't represent (BASELINE.md's budget covers
    it). ``shared`` caps how many distinct series the fake renders; pods
    beyond it serve shared histories by reference (the scanner's work is
    unchanged).

    Rig caveats carry over from the module docstring: ONE core means the
    measured wall-clock is fake-server serving + client read + native parse
    + routing summed, not overlapped — production splits those across
    machines and cores."""
    with _fixture_env(n_containers, samples, shared=shared) as (make_config, one_scan):
        config = make_config(
            strategy="tdigest", other_args={"digest_ingest": True},
            # The wire-shrink headline configuration: compressed transport
            # (the default) + server-side downsampling on the stats route.
            # The pinned scan_end sits on the absolute step grid (the
            # fake's SERIES_ORIGIN is grid-aligned), so eligibility engages
            # exactly as a grid-aligned serve deployment's would; results
            # stay bit-exact vs raw (gated by the wire bench leg + tests).
            fetch_downsample="auto",
        )
        cold_elapsed, cold_stats = one_scan(config)
        # Warm: fake's window bodies cached. Best-of-2, matching the kernel
        # legs' best-of-N convention — a single warm scan put the shared
        # core's ±20% wobble straight into the round record.
        elapsed, stats = min(
            (one_scan(config) for _ in range(2)), key=lambda pair: pair[0]
        )
        # Staged control at the same warm caches: pipeline_depth=0 takes the
        # gather-then-fold path the streamed pipeline replaced, so the round
        # record carries the streamed/staged ratio as one measured pair
        # instead of a cross-round comparison. (Rig caveat: on a core-starved
        # box the stages serialize regardless of overlap, so the ratio there
        # reads the rig, not the pipeline.)
        staged_elapsed, staged_stats = min(
            (one_scan(config.model_copy(update={"pipeline_depth": 0})) for _ in range(2)),
            key=lambda pair: pair[0],
        )
    return {
        "fleet_e2e_containers": int(stats["objects"]),
        "fleet_e2e_objects_per_sec": round(stats["objects"] / elapsed, 1),
        "fleet_e2e_objects_per_sec_cold": round(cold_stats["objects"] / cold_elapsed, 1),
        "fleet_e2e_seconds": round(elapsed, 3),
        "fleet_e2e_cold_seconds": round(cold_elapsed, 3),
        "fleet_e2e_staged_seconds": round(staged_elapsed, 3),
        "fleet_e2e_vs_staged": round(elapsed / staged_elapsed, 3) if staged_elapsed else None,
        "fleet_e2e_overlap_pct": round(stats.get("pipeline_overlap_pct", 0.0), 1),
        "fleet_e2e_pipeline_fetch_seconds": round(stats.get("pipeline_fetch_seconds", 0.0), 3),
        "fleet_e2e_pipeline_fold_seconds": round(stats.get("pipeline_fold_seconds", 0.0), 3),
        # Pipeline wait attribution (PR 6): producer put-blocked = fold-
        # bound, consumer get-starved = fetch-bound — the fetch-vs-fold
        # verdict as a measured pair, not an inference from overlap.
        "fleet_e2e_put_blocked_seconds": round(stats.get("pipeline_put_blocked_seconds", 0.0), 3),
        "fleet_e2e_get_starved_seconds": round(stats.get("pipeline_get_starved_seconds", 0.0), 3),
        # Transport-phase split of the warm fetch leg (summed per-query
        # seconds from krr_tpu_prom_phase_seconds — concurrency means these
        # can exceed the fetch wall; ratios are the signal).
        **{
            f"fleet_e2e_phase_{key.split('prom_phase_')[1]}": round(value, 3)
            for key, value in stats.items()
            if key.startswith("prom_phase_")
        },
        # Wire = bytes off the socket (COMPRESSED under the default
        # --fetch-compression auto — the ROADMAP "sub-GB" target reads off
        # this number); decoded = the post-inflate stream the scanner
        # parsed, so decoded/wire is the measured compression ratio.
        "fleet_e2e_wire_mb": round(stats.get("prom_wire_bytes", 0.0) / 1e6, 1),
        "fleet_e2e_decoded_mb": round(stats.get("prom_decoded_bytes", 0.0) / 1e6, 1),
        "fleet_e2e_wire_ratio": (
            round(stats.get("prom_decoded_bytes", 0.0) / stats["prom_wire_bytes"], 2)
            if stats.get("prom_wire_bytes") and stats.get("prom_gzip_responses")
            else None
        ),
        "fleet_e2e_discover_seconds": round(stats["discover_seconds"], 3),
        "fleet_e2e_fetch_seconds": round(stats["fetch_seconds"], 3),
        "fleet_e2e_compute_seconds": round(stats["compute_seconds"], 3),
        # The ROADMAP target in one number: fetch / (discover + compute).
        # "Fetch within ~2x of discover+compute" means this reads <= ~2.
        "fleet_e2e_fetch_ratio": round(
            stats["fetch_seconds"]
            / max(stats["discover_seconds"] + stats["compute_seconds"], 1e-9),
            3,
        ),
        # Adaptive-plan engagement at fleet width (the 100k single-namespace
        # fixture shards; nothing to coalesce).
        "fleet_e2e_plan_coalesced": stats.get("fetch_plan_coalesced", 0.0),
        "fleet_e2e_plan_sharded": stats.get("fetch_plan_sharded", 0.0),
        # Attribution of the warm wall (round-4 verdict: every second needs
        # an owner): client CPU per phase vs the fake server's CPU. On this
        # 1-core rig the two serialize, so wall ≈ client + server + idle.
        "fleet_e2e_discover_cpu_seconds": round(stats["discover_cpu_seconds"], 3),
        "fleet_e2e_fetch_cpu_seconds": round(stats["fetch_cpu_seconds"], 3),
        "fleet_e2e_compute_cpu_seconds": round(stats["compute_cpu_seconds"], 3),
        # null, not NaN, when /proc isn't readable — NaN is not valid JSON.
        "fleet_e2e_server_cpu_seconds": (
            round(stats["server_cpu_seconds"], 3)
            if stats["server_cpu_seconds"] == stats["server_cpu_seconds"]
            else None
        ),
    }


def run_ingest_throughput(n_series: int = 1000, samples: int = 2688) -> dict:
    """Measure the native scanner's ingest legs on a pre-rendered
    namespace-batched body, no network — the per-core terms of BASELINE.md's
    config-4 wall-clock budget:

    * ``ingest_digest_bytes_per_sec`` — fused parse+digest (the config-4 CPU
      sink: every sample straight into its log bucket);
    * ``ingest_stats_bytes_per_sec`` — parse+count/max (the memory sink);
    * ``ingest_raw_bytes_per_sec`` — raw float64 collection (config 2/3);
    * ``ingest_samples_per_sec`` / ``ingest_bytes_per_sample`` — the measured
      density used in the budget arithmetic.
    """
    import numpy as np

    from krr_tpu.integrations import native

    rng = np.random.default_rng(17)
    fragments = []
    for i in range(n_series):
        values = ",".join(
            f'[{1700000000 + 5 * t},"{float(v)!r}"]'
            for t, v in enumerate(rng.gamma(2.0, 0.05, samples))
        )
        fragments.append(
            '{"metric":{"pod":"wl-%d-0","container":"main"},"values":[%s]}' % (i, values)
        )
    body = (
        '{"status":"success","data":{"resultType":"matrix","result":[%s]}}' % ",".join(fragments)
    ).encode()
    total_samples = n_series * samples

    def best_of(fn, runs=3) -> float:
        fn()  # warm (and build the .so on first use)
        return min(_timed(fn) for _ in range(runs))

    def _timed(fn) -> float:
        start = time.perf_counter()
        fn()
        return time.perf_counter() - start

    digest_s = best_of(lambda: native.parse_matrix_digest(body, 1.01, 1e-7, 2560))
    stats_s = best_of(lambda: native.parse_matrix_stats(body))
    raw_s = best_of(lambda: native.parse_matrix(body))
    return {
        "ingest_bytes_per_sample": round(len(body) / total_samples, 2),
        "ingest_samples_per_sec": round(total_samples / digest_s, 1),
        "ingest_digest_bytes_per_sec": round(len(body) / digest_s, 1),
        "ingest_stats_bytes_per_sec": round(len(body) / stats_s, 1),
        "ingest_raw_bytes_per_sec": round(len(body) / raw_s, 1),
    }


def run_digest_store_scale(n_rows: int = 100_000) -> dict:
    """DigestStore at config-4/5 width: fold a 100k-row window into the
    persistent store, save, and load — the incremental-streaming legs of the
    <60 s steady-state path (BASELINE.md config-4 budget). Counts are
    band-sparse like real fleets (~40 active buckets/row of 2,560)."""
    import numpy as np

    from krr_tpu.core.streaming import DigestStore
    from krr_tpu.ops.digest import DigestSpec

    spec = DigestSpec(gamma=1.01, min_value=1e-7, num_buckets=2560)
    rng = np.random.default_rng(23)
    keys = [f"c/ns-{i % 64}/wl-{i}/main/Deployment" for i in range(n_rows)]
    counts = np.zeros((n_rows, spec.num_buckets), dtype=np.float32)
    bands = rng.integers(200, 2300, size=n_rows)
    for offset in range(40):  # 40 active buckets per row (bands stay < 2560)
        counts[np.arange(n_rows), bands + offset] += rng.integers(1, 60, size=n_rows)
    totals = counts.sum(axis=1)
    peaks = rng.gamma(2.0, 0.3, n_rows).astype(np.float32)

    store = DigestStore(spec=spec)
    start = time.perf_counter()
    store.merge_window(keys, counts, totals, peaks, totals, peaks * 1e3)
    merge_s = time.perf_counter() - start

    start = time.perf_counter()
    rows = np.arange(n_rows)
    p99 = store.cpu_percentile(rows, 99.0)
    query_s = time.perf_counter() - start
    assert np.isfinite(p99).all()

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "state.npz")
        start = time.perf_counter()
        store.save(path)
        save_s = time.perf_counter() - start
        size_mb = os.path.getsize(path) / 1e6
        start = time.perf_counter()
        loaded = DigestStore.load(path)
        load_s = time.perf_counter() - start
        assert len(loaded.keys) == n_rows

    return {
        "digest_store_rows": n_rows,
        "digest_store_merge_seconds": round(merge_s, 3),
        "digest_store_query_p99_seconds": round(query_s, 3),
        "digest_store_save_seconds": round(save_s, 3),
        "digest_store_load_seconds": round(load_s, 3),
        "digest_store_file_mb": round(size_mb, 1),
    }


def run_digest_ingest(n_rows: int) -> dict:
    """Time the digest-ingest compute path (run_digested: host percentile
    query + Decimal finalize + severity-ready raw results) at config-4 fleet
    scale on a synthetic pre-digested fleet."""
    import numpy as np

    from krr_tpu.models.allocations import ResourceAllocations, ResourceType
    from krr_tpu.models.objects import K8sObjectData
    from krr_tpu.models.series import DigestedFleet
    from krr_tpu.strategies.tdigest import TDigestStrategy, TDigestStrategySettings

    settings = TDigestStrategySettings(digest_ingest=True)
    spec = settings.cpu_spec()
    allocations = ResourceAllocations(
        requests={ResourceType.CPU: "100m", ResourceType.Memory: "128Mi"},
        limits={ResourceType.CPU: None, ResourceType.Memory: None},
    )
    objects = [
        K8sObjectData(
            cluster="c", namespace="default", name=f"wl-{i}", kind="Deployment",
            container="main", pods=[f"wl-{i}-0"], allocations=allocations,
        )
        for i in range(n_rows)
    ]
    fleet = DigestedFleet.empty(objects, spec.gamma, spec.min_value, spec.num_buckets)
    rng = np.random.default_rng(9)
    # ~2,000 samples/row spread over a band of buckets; exact values are
    # irrelevant to the timing, the shapes are what matter.
    band = rng.integers(400, 2000, size=n_rows)
    fleet.cpu_counts[np.arange(n_rows), band] = 1500.0
    fleet.cpu_counts[np.arange(n_rows), band + 10] = 500.0
    fleet.cpu_total[:] = 2000.0
    fleet.cpu_peak[:] = 1.0
    fleet.mem_total[:] = 2000.0
    fleet.mem_peak[:] = rng.uniform(5e7, 4e8, n_rows)

    strategy = TDigestStrategy(settings)
    start = time.perf_counter()
    results = strategy.run_digested(fleet)
    elapsed = time.perf_counter() - start
    assert len(results) == n_rows
    return {"digest_ingest_100k_objects_per_sec": round(n_rows / elapsed, 1)}


def main() -> None:
    n = int(os.environ.get("BENCH_E2E_CONTAINERS", 1000))
    samples = int(os.environ.get("BENCH_E2E_SAMPLES", 1344))
    ingest_rows = int(os.environ.get("BENCH_E2E_INGEST_ROWS", 100_000))

    def fleet_leg() -> dict:
        fleet_rows = int(os.environ.get("BENCH_E2E_FLEET_ROWS", 100_000))
        if not fleet_rows:
            return {}
        out = run_fleet_e2e(fleet_rows, samples)
        print(
            f"bench_e2e: FULL fleet scan at {out['fleet_e2e_containers']} containers -> "
            f"{out['fleet_e2e_objects_per_sec']:.0f} objects/s warm "
            f"({out['fleet_e2e_seconds']}s: discover {out['fleet_e2e_discover_seconds']}s, "
            f"fetch {out['fleet_e2e_fetch_seconds']}s (ratio {out['fleet_e2e_fetch_ratio']}), "
            f"compute {out['fleet_e2e_compute_seconds']}s; "
            f"staged control {out['fleet_e2e_staged_seconds']}s -> x{out['fleet_e2e_vs_staged']}, "
            f"pipeline overlap {out['fleet_e2e_overlap_pct']}%, "
            f"waits put {out['fleet_e2e_put_blocked_seconds']}s / "
            f"get {out['fleet_e2e_get_starved_seconds']}s, "
            f"ttfb {out.get('fleet_e2e_phase_ttfb_seconds', 0)}s body {out.get('fleet_e2e_phase_body_read_seconds', 0)}s "
            f"sink {out.get('fleet_e2e_phase_sink_seconds', 0)}s over {out['fleet_e2e_wire_mb']} MB wire"
            f" (decoded {out['fleet_e2e_decoded_mb']} MB, ratio {out['fleet_e2e_wire_ratio']}); "
            f"cold {out['fleet_e2e_cold_seconds']}s; warm CPU split: client fetch "
            f"{out['fleet_e2e_fetch_cpu_seconds']}s, server {out['fleet_e2e_server_cpu_seconds']}s)",
            file=sys.stderr,
        )
        return out

    if int(os.environ.get("BENCH_E2E_FLEET_ONLY", 0)):
        # Fleet-only mode: bench.py runs the ~15-minute full-fleet scan in
        # its own subprocess so a timeout there can't sink the other legs.
        print(json.dumps(fleet_leg()))
        return

    out = run_e2e(n, samples)
    print(
        f"bench_e2e: {out['e2e_containers']} containers x {samples} samples -> "
        f"{out['e2e_objects_per_sec']:.0f} objects/s end-to-end "
        f"(discover {out['discover_seconds']}s, fetch {out['fetch_seconds']}s, "
        f"compute {out['compute_seconds']}s); digest-ingest "
        f"{out['e2e_digest_objects_per_sec']:.0f} objects/s "
        f"(fetch {out['e2e_digest_fetch_seconds']}s)",
        file=sys.stderr,
    )
    if ingest_rows:
        out.update(run_digest_ingest(ingest_rows))
        print(
            f"bench_e2e: digest_ingest at {ingest_rows} rows -> "
            f"{out['digest_ingest_100k_objects_per_sec']:.0f} objects/s",
            file=sys.stderr,
        )
    store_rows = int(os.environ.get("BENCH_E2E_STORE_ROWS", 100_000))
    if store_rows:
        out.update(run_digest_store_scale(store_rows))
        print(
            f"bench_e2e: DigestStore at {store_rows} rows x 2560 buckets -> "
            f"merge {out['digest_store_merge_seconds']}s, p99 query {out['digest_store_query_p99_seconds']}s, "
            f"save {out['digest_store_save_seconds']}s ({out['digest_store_file_mb']} MB), "
            f"load {out['digest_store_load_seconds']}s",
            file=sys.stderr,
        )
    out.update(run_ingest_throughput())
    print(
        f"bench_e2e: scanner ingest {out['ingest_digest_bytes_per_sec']/1e6:.0f} MB/s digest-sink, "
        f"{out['ingest_stats_bytes_per_sec']/1e6:.0f} MB/s stats-sink, "
        f"{out['ingest_raw_bytes_per_sec']/1e6:.0f} MB/s raw "
        f"({out['ingest_bytes_per_sample']} B/sample)",
        file=sys.stderr,
    )
    # Blended transfer+ingest rates for the two streamed digest routes, from
    # the measured bytes/sample density (estimates — the loader doesn't
    # count wire bytes): total samples = containers x samples x 2 resources.
    total_bytes = n * samples * 2 * out["ingest_bytes_per_sample"]
    for route, fetch_key in (
        ("raw", "e2e_digest_fetch_seconds"),
        ("proxied", "e2e_digest_proxied_fetch_seconds"),
    ):
        if out.get(fetch_key):
            out[f"e2e_digest_{route}_blended_mb_per_sec_est"] = round(
                total_bytes / out[fetch_key] / 1e6, 1
            )
    if "e2e_digest_proxied_blended_mb_per_sec_est" in out:
        print(
            f"bench_e2e: streamed digest blended rate — raw transport "
            f"{out.get('e2e_digest_raw_blended_mb_per_sec_est', '?')} MB/s vs proxied (httpx) "
            f"{out['e2e_digest_proxied_blended_mb_per_sec_est']} MB/s (est from B/sample)",
            file=sys.stderr,
        )
    # Standalone runs include the fleet leg inline; bench.py suppresses it
    # here (BENCH_E2E_FLEET_ROWS=0) and runs it via BENCH_E2E_FLEET_ONLY in
    # a second subprocess instead. The long leg runs LAST and fail-soft so a
    # failure can't discard the numbers already measured above.
    try:
        out.update(fleet_leg())
    except Exception as e:
        out["fleet_e2e"] = f"failed: {e.__class__.__name__}"
    print(json.dumps(out))


if __name__ == "__main__":
    main()
