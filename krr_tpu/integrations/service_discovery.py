"""In-cluster service/ingress discovery (used to auto-find Prometheus).

Behavior-compatible with `/root/reference/robusta_krr/utils/service_discovery.py`:
scan Services across all namespaces for each label selector; in-cluster the URL
is the cluster-DNS form, outside it's the apiserver proxy URL (requests then
ride the apiserver's auth); fall back to Ingress hosts; cache results for 15
minutes. (The reference's double ``find_ingress_host`` call — a quirk noted in
SURVEY.md §2.15 — is not reproduced.)
"""

from __future__ import annotations

from typing import Optional

from krr_tpu.integrations.kubernetes import KubeApi
from krr_tpu.utils.logging import KrrLogger, NULL_LOGGER
from krr_tpu.utils.ttl_cache import TTLCache

SERVICE_CACHE_TTL_SEC = 900

#: Well-known Prometheus service selectors (reference `prometheus.py:22-34`).
PROMETHEUS_SELECTORS = [
    "app=kube-prometheus-stack-prometheus",
    "app=prometheus,component=server",
    "app=prometheus-server",
    "app=prometheus-operator-prometheus",
    "app=prometheus-msteams",
    "app=rancher-monitoring-prometheus",
    "app=prometheus-prometheus",
]


class ServiceDiscovery:
    cache: TTLCache = TTLCache(maxsize=8, ttl=SERVICE_CACHE_TTL_SEC)

    def __init__(self, api: KubeApi, inside_cluster: bool, logger: KrrLogger = NULL_LOGGER):
        self.api = api
        self.inside_cluster = inside_cluster
        self.logger = logger

    async def find_service_url(self, label_selector: str) -> Optional[str]:
        # Only the first match is used, but the listing must still page: the
        # apiserver applies label selectors after chunking, so a small `limit`
        # on a selected listing returns empty pages with continue tokens.
        svc = await self.api.first_item("/api/v1/services", labelSelector=label_selector)
        if svc is None:
            return None
        name = svc["metadata"]["name"]
        namespace = svc["metadata"]["namespace"]
        port = svc["spec"]["ports"][0]["port"]
        if self.inside_cluster:
            return f"http://{name}.{namespace}.svc.cluster.local:{port}"
        server = self.api.credentials.server.rstrip("/")
        return f"{server}/api/v1/namespaces/{namespace}/services/{name}:{port}/proxy"

    async def find_ingress_host(self, label_selector: str) -> Optional[str]:
        if self.inside_cluster:
            return None
        ingress = await self.api.first_item(
            "/apis/networking.k8s.io/v1/ingresses", labelSelector=label_selector
        )
        if ingress is None:
            return None
        host = ingress["spec"]["rules"][0]["host"]
        return f"http://{host}"

    async def find_url(self, selectors: list[str]) -> Optional[str]:
        cache_key = (self.api.credentials.server, ",".join(selectors))
        cached = self.cache.get(cache_key)
        if cached:
            return cached
        for selector in selectors:
            self.logger.debug(f"Trying service selector {selector}")
            url = await self.find_service_url(selector)
            if url:
                self.cache[cache_key] = url
                return url
            self.logger.debug(f"Trying ingress selector {selector}")
            url = await self.find_ingress_host(selector)
            if url:
                self.cache[cache_key] = url
                return url
        return None
