"""Deterministic multi-cluster fakes for the federation tests and bench leg.

A :class:`MultiClusterFleet` is N fake clusters with disjoint namespaces and
seeded per-pod series anchored on the evaluation grid, exposed two ways:

* :class:`FleetInventory` — an injectable ``InventorySource`` scoped to a
  cluster subset (the whole fleet for the single-process control, one
  cluster for each shard);
* :class:`WindowedHistory` — an injectable ``HistorySource`` that slices
  each series to the REQUESTED window on the grid (inclusive endpoints,
  like a Prometheus range query), so delta-window semantics are real:
  consecutive delta fetches partition the grid exactly and the federated
  vs single-process bit-exactness comparison is meaningful.

Everything is derived from one seed, so a control scan and a federated scan
over the same clusters see byte-identical ground truth.
"""

from __future__ import annotations

import math

import numpy as np

from krr_tpu.models.allocations import ResourceAllocations, ResourceType
from krr_tpu.models.objects import K8sObjectData

#: Series anchor on the 60 s evaluation grid (divisible by 900 and 60, like
#: the HTTP fakes' SERIES_ORIGIN).
ORIGIN = 1_699_999_200.0
STEP = 60.0


def _allocations(i: int) -> ResourceAllocations:
    return ResourceAllocations(
        requests={ResourceType.CPU: 0.1 * (1 + i % 3), ResourceType.Memory: 128 + 64 * (i % 2)},
        limits={ResourceType.CPU: 0.5, ResourceType.Memory: 512},
    )


class MultiClusterFleet:
    """N clusters × M namespaces × W workloads, with seeded series."""

    def __init__(
        self,
        clusters: int = 3,
        namespaces_per_cluster: int = 2,
        workloads_per_namespace: int = 2,
        pods: int = 2,
        samples: int = 240,
        seed: int = 7,
    ) -> None:
        self.samples = int(samples)
        self.clusters = [f"c{i}" for i in range(clusters)]
        self.objects: dict[str, list[K8sObjectData]] = {}
        self.series: dict[tuple[str, str], tuple[np.ndarray, np.ndarray]] = {}
        rng = np.random.default_rng(seed)
        counter = 0
        for cluster in self.clusters:
            objs: list[K8sObjectData] = []
            for n in range(namespaces_per_cluster):
                namespace = f"{cluster}-ns{n}"
                for w in range(workloads_per_namespace):
                    name = f"app-{w}"
                    pod_names = [f"{name}-pod-{p}" for p in range(pods)]
                    objs.append(
                        K8sObjectData(
                            cluster=cluster,
                            namespace=namespace,
                            name=name,
                            kind="Deployment",
                            container="main",
                            pods=pod_names,
                            allocations=_allocations(counter),
                        )
                    )
                    for pod in pod_names:
                        cpu = np.clip(
                            rng.gamma(2.0, 0.05 * (1 + counter % 4), self.samples), 1e-4, None
                        ).astype(np.float64)
                        mem = rng.uniform(5e7, 4e8, self.samples).astype(np.float64)
                        self.series[(namespace, pod)] = (cpu, mem)
                    counter += 1
            self.objects[cluster] = objs

    def all_objects(self, clusters: "list[str] | None" = None) -> list[K8sObjectData]:
        return [
            obj
            for cluster in (clusters if clusters is not None else self.clusters)
            for obj in self.objects.get(cluster, [])
        ]


class FleetInventory:
    """InventorySource over a cluster subset of one fleet."""

    def __init__(self, fleet: MultiClusterFleet, clusters: "list[str] | None" = None):
        self.fleet = fleet
        self.clusters = list(clusters) if clusters is not None else list(fleet.clusters)
        #: Test knob: clusters whose listing "fails" (fail-soft empty).
        self.failing: set[str] = set()
        self.last_failed_clusters: dict[str, str] = {}

    async def list_clusters(self):
        return list(self.clusters)

    async def list_scannable_objects(self, clusters):
        self.last_failed_clusters = {
            c: "injected discovery failure" for c in (clusters or []) if c in self.failing
        }
        return [
            obj
            for c in (clusters or [])
            if c not in self.failing
            for obj in self.fleet.objects.get(c, [])
        ]


class WindowedHistory:
    """HistorySource for one cluster: grid-sliced deterministic series."""

    def __init__(self, fleet: MultiClusterFleet, cluster: "str | None"):
        self.fleet = fleet
        self.cluster = cluster

    def _slice(self, namespace: str, pod: str, is_cpu: bool, start: float, end: float) -> np.ndarray:
        series = self.fleet.series.get((namespace, pod))
        if series is None:
            return np.empty(0, np.float64)
        values = series[0] if is_cpu else series[1]
        # Inclusive grid endpoints, like a Prometheus range query: samples
        # at ORIGIN + k*STEP with start <= t <= end.
        k0 = max(0, math.ceil((start - ORIGIN) / STEP))
        k1 = min(len(values) - 1, math.floor((end - ORIGIN) / STEP))
        if k1 < k0:
            return np.empty(0, np.float64)
        return values[k0 : k1 + 1]

    async def gather_fleet(self, objects, history_seconds, step_seconds, end_time=None):
        assert end_time is not None, "federation fakes need a pinned window"
        start = float(end_time) - float(history_seconds)
        out = {resource: [] for resource in ResourceType}
        for obj in objects:
            cpu: dict[str, np.ndarray] = {}
            mem: dict[str, np.ndarray] = {}
            for pod in obj.pods:
                cpu_samples = self._slice(obj.namespace, pod, True, start, float(end_time))
                if cpu_samples.size:
                    cpu[pod] = cpu_samples
                mem_samples = self._slice(obj.namespace, pod, False, start, float(end_time))
                if mem_samples.size:
                    mem[pod] = mem_samples
            out[ResourceType.CPU].append(cpu)
            out[ResourceType.Memory].append(mem)
        return out


def history_factory(fleet: MultiClusterFleet):
    return lambda cluster: WindowedHistory(fleet, cluster)


def stores_bitexact_by_key(a, b) -> "tuple[bool, str]":
    """Per-KEY bit-exactness across two stores whose row ORDERS differ (the
    aggregator grows rows in shard-arrival order; a single-process scan in
    discovery order): align rows by key, then compare every digest array
    bit-for-bit."""
    if sorted(a.keys) != sorted(b.keys):
        only_a = set(a.keys) - set(b.keys)
        only_b = set(b.keys) - set(a.keys)
        return False, f"key sets differ (only_a={sorted(only_a)[:3]}, only_b={sorted(only_b)[:3]})"
    index_b = {key: i for i, key in enumerate(b.keys)}
    order = np.asarray([index_b[key] for key in a.keys], dtype=np.int64)
    for attr in ("cpu_counts", "cpu_total", "cpu_peak", "mem_total", "mem_peak"):
        left = getattr(a, attr)
        right = getattr(b, attr)[order]
        if not np.array_equal(left, right):
            bad = int(np.argwhere(~np.isclose(left, right, equal_nan=True))[0][0]) if left.size else -1
            return False, f"{attr} differs (first at row {bad}, key {a.keys[bad] if bad >= 0 else '?'})"
    return True, ""
