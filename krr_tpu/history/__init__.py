"""The recommendation flight recorder (`krr-tpu serve`'s publish memory).

Three pieces, layered under the serve scheduler's publish path:

* :mod:`krr_tpu.history.journal` — an append-only per-workload journal of
  recommendation ticks (compact columnar records keyed by workload identity
  hash, retention-window compaction, crash-safe persistence alongside
  ``--state_path``).
* :mod:`krr_tpu.history.drift` — vectorized drift computation over the
  journal: relative change of the raw recommendation vs the trailing
  published value, flap counting, regime-change detection.
* :mod:`krr_tpu.history.policy` — the hysteresis gate: the published
  recommendation only moves when drift exceeds a dead band for N consecutive
  ticks, so the snapshot the fleet consumes is stable by construction while
  the journal retains the raw series.

:mod:`krr_tpu.history.diff` renders the delta between two journal points (or
journal vs a live scan) through the existing formatter registry — the
``krr-tpu diff`` subcommand.
"""

from krr_tpu.history.journal import RecommendationJournal
from krr_tpu.history.policy import GateDecision, HysteresisGate

__all__ = ["RecommendationJournal", "HysteresisGate", "GateDecision"]
