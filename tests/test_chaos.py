"""Chaos soaks: serve under scripted infrastructure failure.

The acceptance matrix of the fault-isolation work, driven through the chaos
harness (`tests.fakes.chaos`): an archetype fleet served by the real
composition (real PrometheusLoader over real HTTP against the fakes) while a
scripted fault timeline flips outages on and off —

* partial namespace outage → degraded ticks publish the healthy remainder
  with stale marks (no aborted-tick starvation), quarantined workloads carry
  forward their last-good values, and after the faults clear the catch-up
  legs converge the resident store BIT-exact with a never-faulted control
  run;
* hard-down target → ticks abort below the success floor, the circuit
  breaker opens (bounding the degraded-tick wall) and half-open-recovers,
  the scan-failure SLO burns and resolves;
* probabilistic 5xx storms, injected latency, truncated bodies → no crash,
  and recovery is still bit-exact;
* frozen (stale) discovery → inventory changes stay invisible until thaw.

Plus unit tests for the circuit breaker's state machine, the retry budget,
and the capped backoff ladder.
"""

import asyncio
import time

import numpy as np
import pytest

from krr_tpu.core.config import Config
from krr_tpu.integrations.prometheus import (
    BreakerOpenError,
    CircuitBreaker,
    PrometheusLoader,
    RetryBudget,
)
from krr_tpu.obs.metrics import MetricsRegistry

from .fakes.chaos import (
    ORIGIN,
    STEP,
    ArchetypeSpec,
    FaultSpec,
    FaultTimeline,
    ServerThread,
    build_fleet,
    run_soak,
    stores_bitexact,
    write_kubeconfig,
)
from .test_server import http_get, metric_value

TICK = 300.0  # soak scan cadence (seconds of fake clock per scheduler round)


# ------------------------------------------------------------------ fixtures
@pytest.fixture(scope="module")
def chaos_env(tmp_path_factory):
    """One archetype fleet + fake backend shared by the soak scenarios that
    do not mutate the cluster (run_soak heals all fault knobs afterwards)."""
    fleet = build_fleet(samples=240, seed=7)
    server = ServerThread(fleet.backend).start()
    kubeconfig = write_kubeconfig(tmp_path_factory.mktemp("chaos") / "config", server.url)
    yield {"fleet": fleet, "server": server, "kubeconfig": kubeconfig}
    server.stop()


def chaos_config(env, **overrides) -> Config:
    other_args = {"history_duration": 1, "timeframe_duration": 1}
    other_args.update(overrides.pop("other_args", {}))
    defaults = dict(
        kubeconfig=env["kubeconfig"],
        prometheus_url=env["server"].url,
        strategy="tdigest",
        quiet=True,
        server_port=0,
        scan_interval_seconds=TICK,
        # The soak ticks back-to-back in wall time while the scan clock
        # jumps a full cadence: a microscopic breaker cooldown keeps the
        # open → half-open → closed machine observable without wall sleeps,
        # and a small retry budget keeps faulted ticks fast (ladders stop
        # sleeping once it is spent).
        prometheus_breaker_cooldown_seconds=0.02,
        prometheus_retry_deadline_seconds=2.0,
        prometheus_backoff_cap_seconds=0.25,
        other_args=other_args,
    )
    defaults.update(overrides)
    return Config(**defaults)


def run(coro):
    return asyncio.run(coro)


# ----------------------------------------------------- partial-failure soaks
class TestPartialFailureQuarantine:
    def test_namespace_outage_degrades_marks_stale_and_recovers_bitexact(self, chaos_env):
        """THE flap-regime soak: a 3-tick outage of one namespace must not
        starve the fleet — degraded ticks still publish (with stale marks
        and carried-forward values for the quarantined workloads), and once
        the fault clears, catch-up folds converge the store bit-exact with
        a never-faulted control run."""
        env = chaos_env
        timeline = FaultTimeline([(2, 4, FaultSpec(fail_namespaces=frozenset({"diurnal"})))])
        probes: dict = {}

        async def sample_http(server, tick_sample):
            if tick_sample.tick in (1, 3, 7):
                recs = (await http_get(server.port, "/recommendations")).json()
                health = (await http_get(server.port, "/healthz")).json()
                statusz = (await http_get(server.port, "/statusz")).json()
                metrics_text = (await http_get(server.port, "/metrics")).text
                probes[tick_sample.tick] = {
                    "recs": recs, "health": health, "statusz": statusz, "metrics": metrics_text,
                }

        # Hysteresis OFF: published values track the raw recompute, so a
        # frozen quarantined value is carry-forward evidence, not gate
        # behavior — and the final publish comparison below is meaningful.
        # Breaker parked high: this scenario isolates QUARANTINE semantics,
        # and whether the diurnal exhaustions open the breaker mid-outage
        # depends on query interleaving (TestHardDownBreaker owns the
        # breaker's behavior).
        config = dict(hysteresis_enabled=False, prometheus_breaker_threshold=100)
        report = run(
            run_soak(
                chaos_config(env, **config), env["fleet"].backend, timeline,
                ticks=8, tick_seconds=TICK, on_tick=sample_http,
            )
        )
        control = run(
            run_soak(
                chaos_config(env, **config), env["fleet"].backend, None,
                ticks=8, tick_seconds=TICK,
            )
        )

        # No aborted-tick starvation: every tick scanned, the faulted ones
        # degraded (2 of 10 workloads quarantined — far above the floor).
        assert [t.ok for t in report.ticks] == [True] * 8
        assert [t.degraded for t in report.ticks] == [False, False, True, True, True, False, False, False]
        assert [t.stale_workloads for t in report.ticks] == [0, 0, 2, 2, 2, 0, 0, 0]
        assert report.counts()["aborted"] == 0

        # Mid-outage HTTP surface: /healthz counts the quarantine and the
        # tick still advanced the published window; /recommendations marks
        # exactly the diurnal workloads stale, their values frozen at the
        # last pre-fault publish.
        health = probes[3]["health"]
        assert health["status"] == "ok"
        assert health["stale_workloads"] == 2
        assert health["consecutive_scan_failures"] == 0
        assert health["last_scan_unix"] == ORIGIN + 3600.0 + 3 * TICK
        assert probes[3]["statusz"]["server"]["stale_workloads"] == 2
        by_name_pre = {
            s["object"]["name"]: s for s in probes[1]["recs"]["scans"]
        }
        stale_names = set()
        for scan in probes[3]["recs"]["scans"]:
            name = scan["object"]["name"]
            if scan.get("stale_since") is not None:
                stale_names.add(name)
                # Carried forward: bit-identical to the pre-fault publish.
                assert scan["recommended"] == by_name_pre[name]["recommended"]
                # stale_since = the last grid point actually folded (the
                # window end of tick 1).
                assert scan["stale_since"] == ORIGIN + 3600.0 + 1 * TICK
        assert stale_names == {"diurnal-0", "diurnal-1"}
        # Recovery clears the marks (fresh scans OMIT the key entirely —
        # the fleet-scale render pays nothing while healthy).
        assert all("stale_since" not in s for s in probes[7]["recs"]["scans"])
        # The batch-granular failure gauge fired during the outage.
        assert 'krr_tpu_scan_failed_batches' in probes[3]["metrics"]
        assert metric_value(probes[3]["metrics"], "krr_tpu_scan_failed_batches") >= 1

        # Recovery bit-exactness: catch-up folded the union of the missed
        # windows — the store is indistinguishable from never having missed
        # them, and so is the published result.
        equal, detail = stores_bitexact(report.store, control.store)
        assert equal, detail
        assert report.state.peek().body_json == control.state.peek().body_json

        # The quarantine telemetry fired.
        assert report.metrics.value("krr_tpu_scans_degraded_total") == 3
        assert report.metrics.value("krr_tpu_stale_workloads") == 0
        assert (report.metrics.value("krr_tpu_fetch_failed_rows_total") or 0) >= 6

    def test_max_staleness_expires_quarantine_into_full_backfill(self, chaos_env):
        """Carry-forward has a freshness budget: a workload quarantined past
        --max-staleness drops its accumulated row and re-enters as FRESH —
        a full-window backfill once its fetches heal — instead of serving
        ever-older values as "last known good"."""
        env = chaos_env
        timeline = FaultTimeline([(2, 5, FaultSpec(fail_namespaces=frozenset({"oom-loop"})))])
        config = chaos_config(
            env,
            hysteresis_enabled=False,
            prometheus_breaker_threshold=100,  # isolate staleness semantics
            max_staleness_seconds=2 * TICK,
        )
        report = run(run_soak(config, env["fleet"].backend, timeline, ticks=9, tick_seconds=TICK))
        assert all(t.ok for t in report.ticks)
        # Within budget the pair carries forward; the budget trips at tick 4
        # ((i-1)·TICK > 2·TICK), after which the still-faulted pair cycles
        # as failed fresh backfills until the fault clears at tick 6.
        assert [t.stale_workloads for t in report.ticks] == [0, 0, 2, 2, 2, 2, 0, 0, 0]
        assert (report.metrics.value("krr_tpu_quarantine_expired_total") or 0) >= 2
        assert (report.metrics.value("krr_tpu_backfilled_objects_total") or 0) >= 2
        # The recovered rows exist and serve fresh (unmarked) values again.
        oom_keys = [k for k in report.store.keys if "oom-loop" in k]
        assert len(oom_keys) == 2
        final = report.state.peek()
        assert final is not None
        import json as _json

        scans = _json.loads(final.body_json)["scans"]
        assert all("stale_since" not in s for s in scans)

    def test_success_floor_aborts_mostly_dead_ticks(self, chaos_env):
        """Below --min-fetch-success-pct the tick must hard-abort: folding
        and publishing the scraps of a mostly-dead Prometheus would be
        worse than serving the previous result."""
        env = chaos_env
        # 4 of 5 namespaces out = 20% success, under the 50% floor.
        dead = frozenset({"diurnal", "bursty-batch", "oom-loop", "high-churn"})
        timeline = FaultTimeline([(1, 2, FaultSpec(fail_namespaces=dead))])
        report = run(
            run_soak(
                chaos_config(env), env["fleet"].backend, timeline,
                ticks=5, tick_seconds=TICK,
            )
        )
        assert [t.ok for t in report.ticks] == [True, None, None, True, True]
        # Aborted ticks quarantine nothing — the window simply refetches.
        assert [t.stale_workloads for t in report.ticks] == [0, 0, 0, 0, 0]
        assert [t.consecutive_failures for t in report.ticks] == [0, 1, 2, 0, 0]
        assert report.state.last_scan_error is not None
        assert "min-fetch-success-pct" in report.state.last_scan_error


# ------------------------------------------------------- hard-down + breaker
class TestHardDownBreaker:
    def test_breaker_opens_bounds_wall_and_half_open_recovers(self, chaos_env):
        """One Prometheus target hard-down: ticks abort below the floor, the
        breaker opens (so degraded ticks complete within a bounded wall —
        fail-fast, not a retry ladder per query), and once the target heals
        a half-open probe closes it; the scan-failure SLO burns during the
        outage and resolves after."""
        env = chaos_env
        timeline = FaultTimeline([(2, 5, FaultSpec(down=True))])
        report = run(
            run_soak(
                chaos_config(env), env["fleet"].backend, timeline,
                ticks=12, tick_seconds=TICK,
            )
        )
        down = report.ticks[2:6]
        recovered = report.ticks[6:]

        # Outage ticks abort (0% success); recovery is immediate and clean —
        # the first healthy tick's probe succeeds and the parked queries run
        # behind it (no recovery wave sacrificed to probe timing).
        assert [t.ok for t in down] == [None] * 4
        assert [t.consecutive_failures for t in down] == [1, 2, 3, 4]
        assert all(t.ok for t in recovered)
        assert recovered[0].consecutive_failures == 0
        assert recovered[-1].stale_workloads == 0

        # Bounded wall: the retry budget plus breaker fail-fast keep every
        # down tick's wall in seconds, not ladders x queries. (The budget
        # alone allows 2s of backoff; everything past it is fail-fast.)
        clean_wall = max(t.wall_seconds for t in report.ticks[:2])
        for t in down:
            assert t.wall_seconds < 8.0, (t.tick, t.wall_seconds)
        # Fail-fast did engage: an open breaker turned queries away with
        # zero I/O.
        assert (report.metrics.value("krr_tpu_prom_breaker_fast_failures_total", cluster="fake") or 0) > 0

        # Breaker lifecycle: opened during the outage, half-open probed,
        # closed on recovery, and ended closed.
        opens = report.metrics.value(
            "krr_tpu_prom_breaker_transitions_total", cluster="fake", to="open"
        )
        half_opens = report.metrics.value(
            "krr_tpu_prom_breaker_transitions_total", cluster="fake", to="half_open"
        )
        closes = report.metrics.value(
            "krr_tpu_prom_breaker_transitions_total", cluster="fake", to="closed"
        )
        assert opens and opens >= 1
        assert half_opens and half_opens >= 1
        assert closes and closes >= 1
        assert report.ticks[-1].breaker_state == 0.0
        assert any(t.breaker_state == 2.0 for t in down)

        # SLO loop: scan_failures fires during the outage, resolves after.
        assert any("scan_failures" in t.slo_firing for t in down)
        assert report.ticks[-1].slo_firing == []
        # Sanity: the clean ticks were far faster than the bound we allow
        # faulted ones (guards against the bound going vacuous).
        assert clean_wall < 8.0


# ----------------------------------------------- storms, latency, truncation
class TestStormLatencyTruncation:
    def test_mixed_regime_soak_recovers_bitexact(self, chaos_env):
        """A scripted mixed regime — 5xx storm, injected latency, truncated
        bodies — must never crash the scheduler, and whatever mix of
        degraded and aborted ticks it produces, the post-recovery store
        must still converge bit-exact with the never-faulted control."""
        env = chaos_env
        timeline = FaultTimeline(
            [
                (1, 2, FaultSpec(fail_rate=0.8, fault_seed=3)),
                (3, 3, FaultSpec(latency_seconds=0.15)),
                (4, 4, FaultSpec(truncate_bodies=True)),
            ]
        )
        report = run(
            run_soak(
                chaos_config(env), env["fleet"].backend, timeline,
                ticks=9, tick_seconds=TICK,
            )
        )
        control = run(
            run_soak(
                chaos_config(env), env["fleet"].backend, None,
                ticks=9, tick_seconds=TICK,
            )
        )
        # The latency tick merely slows the scan; the truncation tick fails
        # every parse (terminal, no retry storm) and aborts below the floor.
        assert report.ticks[3].ok is True
        assert report.ticks[4].ok is None
        # Clean tail: everything recovered and nothing is still stale.
        assert all(t.ok for t in report.ticks[5:])
        assert report.ticks[-1].stale_workloads == 0
        equal, detail = stores_bitexact(report.store, control.store)
        assert equal, detail

    def test_frozen_discovery_hides_inventory_changes_until_thaw(self, tmp_path):
        """Stale discovery: while the apiserver serves a frozen snapshot, a
        new deployment stays invisible; the thawed discovery picks it up
        and backfills it."""
        fleet = build_fleet(
            (ArchetypeSpec("mixed-qos", workloads=2, pods=1),), samples=240, seed=3
        )
        server = ServerThread(fleet.backend).start()
        try:
            kubeconfig = write_kubeconfig(tmp_path / "config", server.url)
            env = {"kubeconfig": kubeconfig, "server": server}
            # Freeze spans ticks 0-2: the snapshot is taken BEFORE tick 0
            # runs, so the mutation at the end of tick 0 stays invisible
            # through tick 2 and surfaces at the tick-3 rediscovery.
            timeline = FaultTimeline([(0, 2, FaultSpec(freeze_discovery=True))])

            def mutate(server_obj, tick_sample):
                if tick_sample.tick == 0:
                    # Appears AFTER the freeze snapshot was captured.
                    pods = fleet.cluster.add_workload_with_pods(
                        "Deployment", "late-arrival", "mixed-qos", pod_count=1
                    )
                    rng = np.random.default_rng(11)
                    for pod in pods:
                        fleet.metrics.set_series(
                            "mixed-qos", "main", pod,
                            cpu=rng.uniform(0.1, 0.2, 240), memory=rng.uniform(1e8, 2e8, 240),
                        )

            report = run(
                run_soak(
                    chaos_config(env, discovery_interval_seconds=1.0),
                    fleet.backend,
                    timeline,
                    ticks=5,
                    tick_seconds=TICK,
                    on_tick=mutate,
                )
            )
            assert all(t.ok for t in report.ticks)
            # Frozen ticks (1, 2) kept serving the 2-workload inventory;
            # the thawed tick discovered and backfilled the third.
            assert len(report.store.keys) == 3
            assert (report.metrics.value("krr_tpu_backfilled_objects_total") or 0) >= 1
            assert report.metrics.value("krr_tpu_fleet_objects") == 3
        finally:
            server.stop()

    def test_churn_rotation_compacts_and_backfills(self, tmp_path):
        """High-churn archetype: deployments replaced mid-soak — the old
        rows compact away, the replacements backfill, and the soak stays
        healthy throughout."""
        fleet = build_fleet(
            (ArchetypeSpec("high-churn", workloads=3, pods=1),), samples=240, seed=5
        )
        server = ServerThread(fleet.backend).start()
        try:
            kubeconfig = write_kubeconfig(tmp_path / "config", server.url)
            env = {"kubeconfig": kubeconfig, "server": server}
            rng = np.random.default_rng(13)

            def rotate(server_obj, tick_sample):
                if tick_sample.tick == 1:
                    # Replace high-churn-0 with high-churn-3.
                    fleet.cluster.deployments = [
                        d for d in fleet.cluster.deployments
                        if d["metadata"]["name"] != "high-churn-0"
                    ]
                    fleet.cluster.pods = [
                        p for p in fleet.cluster.pods
                        if not p["metadata"]["name"].startswith("high-churn-0-")
                    ]
                    pods = fleet.cluster.add_workload_with_pods(
                        "Deployment", "high-churn-3", "high-churn", pod_count=1
                    )
                    for pod in pods:
                        fleet.metrics.set_series(
                            "high-churn", "main", pod,
                            cpu=rng.uniform(0.05, 0.3, 240), memory=rng.uniform(1e8, 2e8, 240),
                        )

            report = run(
                run_soak(
                    chaos_config(env, discovery_interval_seconds=1.0),
                    fleet.backend,
                    None,
                    ticks=4,
                    tick_seconds=TICK,
                    on_tick=rotate,
                )
            )
            assert all(t.ok for t in report.ticks)
            keys = set(report.store.keys)
            assert not any("/high-churn-0/" in k for k in keys)
            assert any("/high-churn-3/" in k for k in keys)
            assert (report.metrics.value("krr_tpu_store_compacted_rows_total") or 0) >= 1
        finally:
            server.stop()


# -------------------------------------------------- breaker/budget unit tests
class TestCircuitBreakerUnit:
    def make(self, **overrides):
        now = [1000.0]
        defaults = dict(threshold=3, cooldown=30.0, cluster="c", clock=lambda: now[0])
        defaults.update(overrides)
        registry = defaults.setdefault("metrics", MetricsRegistry())
        return CircuitBreaker(defaults.pop("threshold"), defaults.pop("cooldown"), **defaults), now, registry

    def test_opens_after_threshold_and_fails_fast(self):
        async def main():
            breaker, now, registry = self.make()
            for _ in range(3):
                assert await breaker.admit() is False
                breaker.record_failure(False)
            assert breaker.state == "open"
            with pytest.raises(BreakerOpenError):
                await breaker.admit()
            assert registry.value("krr_tpu_prom_breaker_state", cluster="c") == 2.0
            assert registry.value("krr_tpu_prom_breaker_fast_failures_total", cluster="c") == 1.0

        asyncio.run(main())

    def test_half_open_probe_parks_waiters_then_closes(self):
        async def main():
            breaker, now, registry = self.make()
            for _ in range(3):
                breaker.record_failure(False)
            now[0] += 31.0  # cooldown elapsed: next admit is THE probe
            probe = await breaker.admit()
            assert probe is True and breaker.state == "half_open"
            # A concurrent query PARKS on the probe instead of failing fast…
            waiter = asyncio.ensure_future(breaker.admit())
            await asyncio.sleep(0)
            assert not waiter.done()
            # …and proceeds as an ordinary query once the probe succeeds.
            breaker.record_success(probe)
            assert await waiter is False
            assert breaker.state == "closed" and breaker.failures == 0
            assert await breaker.admit() is False  # flow restored
            assert registry.value(
                "krr_tpu_prom_breaker_transitions_total", cluster="c", to="closed"
            ) == 1.0

        asyncio.run(main())

    def test_probe_failure_reopens_and_fails_waiters(self):
        async def main():
            breaker, now, _ = self.make()
            for _ in range(3):
                breaker.record_failure(False)
            now[0] += 31.0
            probe = await breaker.admit()
            waiter = asyncio.ensure_future(breaker.admit())
            await asyncio.sleep(0)
            breaker.record_failure(probe)
            assert breaker.state == "open"
            with pytest.raises(BreakerOpenError):  # the parked query fails fast
                await waiter
            with pytest.raises(BreakerOpenError):  # new cooldown from the probe
                await breaker.admit()
            now[0] += 31.0
            assert await breaker.admit() is True  # probes again

        asyncio.run(main())

    def test_abandoned_probe_releases_waiters_and_reopens(self):
        """A probe cancelled mid-ladder must not strand parked queries on a
        future nobody settles — they fail fast, the breaker re-opens with a
        fresh cooldown, and only after it elapses does the next query probe."""

        async def main():
            breaker, now, _ = self.make()
            for _ in range(3):
                breaker.record_failure(False)
            now[0] += 31.0
            probe = await breaker.admit()
            assert probe is True
            waiter = asyncio.ensure_future(breaker.admit())
            await asyncio.sleep(0)
            breaker.abandon_probe()
            with pytest.raises(BreakerOpenError):
                await waiter
            assert breaker.state == "open"
            with pytest.raises(BreakerOpenError):  # cooldown restarted
                await breaker.admit()
            now[0] += 31.0
            assert await breaker.admit() is True  # a fresh probe slot

        asyncio.run(main())

    def test_success_epoch_discounts_overlapped_failures(self):
        """A failing ladder that overlapped a sibling's SUCCESS (the epoch
        moved between admit and failure) must not count toward opening —
        one broken namespace's slow ladders always overlap its healthy
        siblings' fast successes, and a live target must stay admitted."""
        breaker, _, _ = self.make()
        for _ in range(20):
            epoch = breaker.success_epoch
            breaker.record_success(False)  # a healthy sibling completes
            breaker.record_failure(False, epoch=epoch)  # stale epoch: discounted
        assert breaker.state == "closed" and breaker.failures == 0
        # Without interleaved successes the same epochs count and open it.
        for _ in range(3):
            breaker.record_failure(False, epoch=breaker.success_epoch)
        assert breaker.state == "open"

    def test_any_http_answer_resets_consecutive_failures(self):
        """A 4xx means the target is alive: the breaker must not open on
        bad queries interleaved with transport blips."""
        breaker, _, _ = self.make()
        for _ in range(10):
            breaker.record_failure(False)
            breaker.record_success(False)  # e.g. a 400 on the next query
        assert breaker.state == "closed"

    def test_threshold_zero_disables(self):
        async def main():
            breaker, _, _ = self.make(threshold=0)
            for _ in range(50):
                assert await breaker.admit() is False
                breaker.record_failure(False)
            assert breaker.state == "closed"

        asyncio.run(main())


class TestRetryBudgetUnit:
    def test_budget_charges_and_exhausts(self):
        budget = RetryBudget(1.0)
        assert budget.consume(0.4) and budget.consume(0.4)
        assert not budget.consume(0.4)  # 1.2 > 1.0
        assert budget.note_exhausted() and not budget.note_exhausted()
        budget.reset()
        assert budget.consume(0.9) and budget.note_exhausted()

    def test_zero_budget_is_unlimited(self):
        budget = RetryBudget(0.0)
        assert all(budget.consume(10.0) for _ in range(100))
        assert budget.spent == 0.0


class TestBackoffCapAndBudgetLadder:
    def test_backoff_sleeps_are_capped_and_budgeted(self, monkeypatch):
        """Drive the real retry ladder against an always-500 endpoint with
        a deep retry count: every backoff sleep must respect the pre-jitter
        cap, and the ladder must stop sleeping once the scan budget is
        spent (the failure then surfaces terminally)."""
        from tests.fakes.servers import FakeBackend, FakeCluster, FakeMetrics

        metrics_fake = FakeMetrics()
        metrics_fake.fail_queries = True
        server = ServerThread(FakeBackend(FakeCluster(), metrics_fake)).start()
        try:
            config = Config(
                prometheus_url=server.url,
                prometheus_backoff_cap_seconds=0.05,
                prometheus_retry_deadline_seconds=0.2,
                prometheus_breaker_threshold=0,  # isolate the ladder
            )
            sleeps: list = []
            real_sleep = asyncio.sleep

            class _AsyncioProxy:
                """asyncio with a recording sleep — swapped into the prom
                module's globals only, so the fake server's event loop (a
                different thread using the REAL asyncio) is untouched."""

                def __getattr__(self, name):
                    return getattr(asyncio, name)

                @staticmethod
                async def sleep(wait, *args, **kwargs):
                    sleeps.append(wait)
                    await real_sleep(0)

            import krr_tpu.integrations.prometheus as prom_module

            monkeypatch.setattr(prom_module, "asyncio", _AsyncioProxy())

            async def go():
                loader = PrometheusLoader(config)
                loader.retries = 12
                try:
                    with pytest.raises(Exception):
                        await loader._fetch_range_body("q", 0.0, 60.0, "1m")
                finally:
                    await loader.close()
                return loader

            loader = asyncio.run(go())
            # Jitter tops out at 1.5x the capped base.
            assert sleeps, "ladder never slept"
            assert max(sleeps) <= 0.05 * 1.5 + 1e-9
            # The budget stopped the ladder long before 11 retries.
            assert sum(sleeps) <= 0.2
            assert len(sleeps) < 11
            assert loader.retry_budget.spent <= 0.2
        finally:
            server.stop()


# ------------------------------------------------------ durable-store chaos
class TestPersistFaultDegrade:
    def test_enospc_during_save_degrades_and_recovers(self, chaos_env, tmp_path):
        """The ISSUE's ENOSPC acceptance scenario, end to end over the real
        composition: disk faults during the store persist must NOT kill the
        tick — serve keeps publishing from memory, /healthz reports
        degraded with krr_tpu_persist_failures_total incrementing, and the
        first fault-free tick persists the whole backlog."""
        env = chaos_env
        state_path = str(tmp_path / "state")
        from tests.fakes.chaos import FaultyFs
        from krr_tpu.core.streaming import FS

        faulty = FaultyFs(("append", "fsync"))
        probes: dict = {}

        async def on_tick(server, sample):
            if sample.tick == 0:
                # Install the disk fault for ticks 1-2 on THIS store only.
                server.scheduler.durable.fs = faulty
            if sample.tick == 2:
                server.scheduler.durable.fs = FS  # fault clears before tick 3
            if sample.tick in (1, 2, 3):
                health = (await http_get(server.port, "/healthz")).json()
                probes[sample.tick] = {
                    "health": health,
                    "failures": metric_value(
                        (await http_get(server.port, "/metrics")).text,
                        "krr_tpu_persist_failures_total",
                    ),
                    "epoch": server.scheduler.durable.epoch,
                    "pending": len(server.state.store.pending_ops()),
                }

        config = chaos_config(
            env,
            hysteresis_enabled=False,
            other_args={"state_path": state_path},
        )
        report = run(
            run_soak(config, env["fleet"].backend, None, ticks=4, tick_seconds=TICK,
                     on_tick=on_tick)
        )

        # Every tick published — persist faults degrade, never abort.
        assert [t.ok for t in report.ticks] == [True] * 4
        # Mid-fault posture: degraded verdict, counter climbing, epoch
        # parked, backlog queued.
        assert probes[1]["health"]["status"] == "degraded"
        assert probes[1]["health"]["persist_failing"] is True
        assert probes[1]["health"]["last_persist_error"]
        assert probes[1]["failures"] == 1.0
        assert probes[2]["failures"] == 2.0
        assert probes[2]["epoch"] == probes[1]["epoch"] == 1  # only tick 0 persisted
        assert probes[2]["pending"] > 0
        # Fault-free tick 3: persists the backlog in one record, recovers
        # the verdict.
        assert probes[3]["health"]["status"] == "ok"
        assert probes[3]["health"]["persist_failing"] is False
        assert probes[3]["health"]["persist_failures"] == 2
        assert probes[3]["epoch"] == 2 and probes[3]["pending"] == 0

        # The recovered directory holds exactly the in-memory final state.
        from krr_tpu.core.durastore import DurableStore
        from krr_tpu.strategies.tdigest import TDigestStrategySettings

        disk = DurableStore.open(state_path, TDigestStrategySettings().cpu_spec())
        equal, detail = stores_bitexact(disk.store, report.store)
        assert equal, detail
        assert disk.store.extra_meta["serve_last_end"] == report.store.extra_meta["serve_last_end"]
        disk.close()


class TestScanSentinelSoak:
    """The flight-recorder acceptance criteria (`krr_tpu.obs.timeline` /
    `krr_tpu.obs.sentinel`) driven through the REAL serve composition: a
    mid-run injected Prometheus latency regime must produce a sentinel
    verdict attributed to fetch_transport within 3 ticks of onset, and a
    long clean-control soak must produce ZERO regression verdicts."""

    ONSET = 12  # the latency regime starts here (after the warm-up window)

    def _config(self, env, state_path=None, **overrides):
        other_args = {}
        if state_path is not None:
            other_args["state_path"] = state_path
        return chaos_config(
            env,
            hysteresis_enabled=False,
            sentinel_warmup_scans=6,
            # CI-robust bands at toy scale: a clean tick's categories sit in
            # the tens of milliseconds, so the absolute floor makes a
            # verdict require ≥ 1.2 s of excess — far above even a loaded
            # box's scheduler stalls, far below the injected latency's
            # multi-second transport bulge.
            sentinel_abs_floor_seconds=0.4,
            other_args={**other_args},
            **overrides,
        )

    def test_latency_regime_attributed_to_fetch_transport_within_3_ticks(self, chaos_env):
        env = chaos_env
        onset = self.ONSET
        timeline = FaultTimeline([(onset, onset + 3, FaultSpec(latency_seconds=1.0))])
        verdicts: "list[tuple[int, dict]]" = []

        def on_tick(server, sample):
            sentinel = server.state.sentinel
            if sentinel is not None and sentinel.last_verdict is not None:
                verdicts.append((sample.tick, dict(sentinel.last_verdict)))

        report = run(
            run_soak(
                self._config(env), env["fleet"].backend, timeline,
                ticks=onset + 5, tick_seconds=TICK, on_tick=on_tick,
            )
        )
        assert all(t.ok for t in report.ticks)  # latency slows, never aborts
        regressed = [
            (tick, v) for tick, v in verdicts if v.get("status") == "regressed"
        ]
        assert regressed, "sentinel never fired across the latency regime"
        first_tick, first = regressed[0]
        # Within 3 ticks of onset, attributed to the transport category.
        assert onset <= first_tick <= onset + 2, f"first verdict at tick {first_tick}"
        assert first["dominant"] == "fetch_transport", first
        assert first["sigma"] >= 3.0
        assert "Prometheus" in first["suspect"] or "transport" in first["suspect"]
        # The verdict also fired as the metric and counted toward the totals.
        assert (
            report.metrics.value(
                "krr_tpu_scan_regressions_total", category="fetch_transport"
            )
            or 0.0
        ) >= 1.0
        # No pre-onset false positives (the post-onset clean tail may still
        # flag while the elevated scans are excluded from the baseline).
        assert all(tick >= onset for tick, _v in regressed)

    def test_50_tick_clean_control_has_zero_verdicts(self, chaos_env):
        env = chaos_env
        report = run(
            run_soak(
                self._config(env), env["fleet"].backend, None,
                ticks=50, tick_seconds=TICK,
            )
        )
        assert all(t.ok for t in report.ticks)
        sentinel = report.state.sentinel
        assert sentinel.warmed("delta")
        assert sentinel.classified_scans >= 40
        assert sentinel.regressed_scans == 0, sentinel.last_verdict
        assert (report.metrics.total("krr_tpu_scan_regressions_total") or 0.0) == 0.0
        # 50 records on the in-memory recorder (no state path configured).
        assert len(report.state.timeline.records()) == 50


class TestSigkillSoak:
    def test_sigkill_soak_restarts_to_last_durable_publish_bitexact(self, tmp_path):
        """THE acceptance soak: a real serve subprocess over the chaos
        fakes, SIGKILLed at 8 random points across a 10-tick schedule
        (mid-fetch, mid-fold, mid-journal-append, mid-WAL-append, and —
        with the compaction floor forced tiny — mid-compaction), restarted
        from the same state directory each time. Every restart must
        reconstruct the last durable publish (an unrecoverable store fails
        the rerun loudly), and the completed schedule must converge BIT-
        exact with a never-killed control run — store arrays, key order,
        and window cursor alike."""
        import os

        from tests.fakes.chaos import run_kill_soak

        fleet = build_fleet(
            (
                ArchetypeSpec("diurnal", workloads=2, pods=1),
                ArchetypeSpec("bursty-batch", workloads=2, pods=1),
            ),
            samples=240,
            seed=13,
        )
        server = ServerThread(fleet.backend).start()
        try:
            kubeconfig = write_kubeconfig(tmp_path / "kubeconfig", server.url)
            state = str(tmp_path / "state")
            control = str(tmp_path / "control")

            def payload(state_path: str) -> dict:
                return dict(
                    kubeconfig=kubeconfig,
                    prometheus_url=server.url,
                    strategy="tdigest",
                    quiet=True,
                    server_port=0,
                    scan_interval_seconds=TICK,
                    hysteresis_enabled=False,
                    # Tiny compaction floor: the WAL crosses it every few
                    # ticks, so kills also land inside compactions and
                    # restarts recover across manifest flips.
                    store_compact_min_wal_mb=0.002,
                    prometheus_retry_deadline_seconds=1.0,
                    prometheus_backoff_cap_seconds=0.2,
                    other_args={
                        "history_duration": 1,
                        "timeframe_duration": 1,
                        "state_path": state_path,
                    },
                )

            ticks = [ORIGIN + 3600.0 + i * TICK for i in range(10)]
            env = {**os.environ, "JAX_PLATFORMS": "cpu"}
            repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            report = run_kill_soak(
                payload(state), ticks, kills=8, seed=17,
                cfg_path=str(tmp_path / "soak.json"), repo_root=repo, env=env,
            )
            assert report["kills"] == 8
            assert report["runs"] >= 9  # 8 killed runs + >=1 completing run
            run_kill_soak(
                payload(control), ticks, kills=0, seed=18,
                cfg_path=str(tmp_path / "control.json"), repo_root=repo, env=env,
            )
        finally:
            server.stop()

        from krr_tpu.core.durastore import DurableStore
        from krr_tpu.strategies.tdigest import TDigestStrategySettings

        spec = TDigestStrategySettings().cpu_spec()
        soaked = DurableStore.open(state, spec)
        clean = DurableStore.open(control, spec)
        equal, detail = stores_bitexact(soaked.store, clean.store)
        assert equal, detail
        assert soaked.store.extra_meta["serve_last_end"] == clean.store.extra_meta["serve_last_end"]
        # Both runs' stores saw every tick: the soaked one compacted at
        # least once (the tiny floor guarantees it), and its epoch counts
        # every durable publish the control made.
        assert soaked.epoch == clean.epoch == len(ticks)
        soaked.close()

        # --- the flight recorder's SIGKILL leg (`krr_tpu.obs.timeline`) ---
        from krr_tpu.obs.sentinel import RegressionSentinel
        from krr_tpu.obs.timeline import ScanTimeline

        soaked_path = os.path.join(state, "timeline.log")
        control_path = os.path.join(control, "timeline.log")
        soaked_recs = ScanTimeline.read_records(soaked_path)
        control_recs = ScanTimeline.read_records(control_path)
        # The never-killed control recorded every tick; the killed run may
        # have lost records for ticks killed between the store persist and
        # the timeline append (their windows are folded, never re-run) but
        # records most of the schedule.
        assert len(control_recs) == len(ticks)
        assert len(soaked_recs) >= len(ticks) - 8 and len(soaked_recs) >= 2
        # Recovery truncated cleanly: re-OPENING the killed timeline is a
        # no-op — the file read back is bit-identical to itself up to the
        # last durable record (no torn bytes survived the kills).
        before = open(soaked_path, "rb").read()
        reopened = ScanTimeline.open(soaked_path)
        assert reopened.records() == soaked_recs
        reopened.close()
        assert open(soaked_path, "rb").read() == before
        # Structural agreement with the control at every shared tick: the
        # recorded schedule is an ordered subset with identical window
        # geometry and fleet shape (timing fields differ run to run).
        by_ts = {r["ts"]: r for r in control_recs}
        assert [r["ts"] for r in soaked_recs] == sorted(r["ts"] for r in soaked_recs)
        for record in soaked_recs:
            twin = by_ts.get(record["ts"])
            assert twin is not None, f"tick {record['ts']} missing from control"
            for field in ("kind", "rows", "failed_rows", "window_seconds"):
                assert record[field] == twin[field], (field, record["ts"])
        # Sentinel baselines survive the restarts: a sentinel seeded from
        # the recovered timeline is warm without any re-warm-up window.
        sentinel = RegressionSentinel(warmup_scans=4)
        sentinel.seed(soaked_recs)
        assert sentinel.warmed("delta")
        clean.close()
