"""Fake Prometheus remote-write sender: hand-rolled protobuf + snappy encoder.

Builds on-the-wire `WriteRequest` bodies (snappy block-compressed protobuf,
remote-write 1.0) from a :class:`FakeMetrics` series table, so ingest tests
drive the listener with byte-realistic frames without a protobuf or snappy
dependency. The compressor emits literal-only snappy (always valid, never
clever); `encode_write_request` mirrors the real field numbering:

    WriteRequest{1: repeated TimeSeries}
    TimeSeries{1: repeated Label, 2: repeated Sample}
    Label{1: name, 2: value}          Sample{1: double value, 2: int64 ts_ms}

Samples ride the same grid the fake Prometheus serves (`SERIES_ORIGIN` +
i*step), so a push-fed window and a range-fetched window see identical data —
the bit-exactness gate's precondition.
"""

from __future__ import annotations

import struct

import numpy as np

from .servers import FakeBackend, FakeMetrics

#: The two series shapes the recommender consumes, labelled the way a real
#: kube-prometheus stack ships them (the ingest router matches on these).
CPU_METRIC = "node_namespace_pod_container:container_cpu_usage_seconds_total:sum_irate"
MEM_METRIC = "container_memory_working_set_bytes"


# ---------------------------------------------------------------- primitives
def uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def snappy_compress(data: bytes) -> bytes:
    """Literal-only snappy block encoding: length preamble + 60-bit-capped
    literal runs. Valid input for any conformant decoder; no copy tags."""
    out = bytearray(uvarint(len(data)))
    pos = 0
    while pos < len(data):
        chunk = data[pos : pos + 65536]
        pos += len(chunk)
        if len(chunk) <= 60:
            out.append((len(chunk) - 1) << 2)
        else:  # tag 60+k: k little-endian length bytes follow
            out.append(61 << 2)
            out += struct.pack("<H", len(chunk) - 1)
        out += chunk
    return bytes(out)


def _pb_field(field: int, payload: bytes) -> bytes:
    return uvarint(field << 3 | 2) + uvarint(len(payload)) + payload


def encode_label(name: str, value: str) -> bytes:
    return _pb_field(1, name.encode()) + _pb_field(2, value.encode())


def encode_sample(value: float, ts_ms: int) -> bytes:
    return (
        uvarint(1 << 3 | 1)
        + struct.pack("<d", value)
        + uvarint(2 << 3 | 0)
        + uvarint(ts_ms & (1 << 64) - 1)  # int64 two's complement
    )


def encode_timeseries(labels: list[tuple[str, str]], samples: list[tuple[float, int]]) -> bytes:
    body = b"".join(_pb_field(1, encode_label(n, v)) for n, v in labels)
    body += b"".join(_pb_field(2, encode_sample(v, ts)) for v, ts in samples)
    return body


def encode_write_request(series: list[tuple[list[tuple[str, str]], list[tuple[float, int]]]]) -> bytes:
    return b"".join(_pb_field(1, encode_timeseries(labels, samples)) for labels, samples in series)


def build_body(series) -> bytes:
    """series → the on-the-wire POST body (snappy over protobuf)."""
    return snappy_compress(encode_write_request(series))


# ------------------------------------------------------------------- sender
def cpu_labels(namespace: str, pod: str, container: str) -> list[tuple[str, str]]:
    return [
        ("__name__", CPU_METRIC),
        ("container", container),
        ("namespace", namespace),
        ("pod", pod),
    ]


def mem_labels(namespace: str, pod: str, container: str) -> list[tuple[str, str]]:
    # The cadvisor label baggage the router's mem filters require
    # (job/metrics_path, a non-empty image).
    return [
        ("__name__", MEM_METRIC),
        ("container", container),
        ("image", "registry.example/app:1"),
        ("job", "kubelet"),
        ("metrics_path", "/metrics/cadvisor"),
        ("namespace", namespace),
        ("pod", pod),
    ]


class RemoteWriteSender:
    """Streams a FakeMetrics series table to an ingest listener, one grid
    index range at a time — the push twin of the fake's range-query serving
    (same origin, same step, same values)."""

    def __init__(
        self,
        metrics: FakeMetrics,
        step_seconds: float = 60.0,
        origin: float = FakeBackend.SERIES_ORIGIN,
        container_override: str | None = None,
    ):
        self.metrics = metrics
        self.step_seconds = float(step_seconds)
        self.origin = float(origin)
        self.container_override = container_override

    def ts_ms(self, index: int) -> int:
        return int(round((self.origin + index * self.step_seconds) * 1000.0))

    def frames(self, i0: int, i1: int) -> bytes:
        """One body carrying sample indices [i0, i1] (inclusive, clipped to
        each series' length) for every series the fake serves."""
        series = []
        for (namespace, container, pod), (cpu, mem) in sorted(self.metrics.series.items()):
            container = self.container_override or container
            for labels, values in (
                (cpu_labels(namespace, pod, container), cpu),
                (mem_labels(namespace, pod, container), mem),
            ):
                lo, hi = max(i0, 0), min(i1, len(values) - 1)
                samples = [(float(values[i]), self.ts_ms(i)) for i in range(lo, hi + 1)]
                if samples:
                    series.append((labels, samples))
        return build_body(series)

    async def push(self, port: int, i0: int, i1: int, host: str = "127.0.0.1") -> int:
        """POST indices [i0, i1] to a listener; returns the HTTP status."""
        return await post_body(port, self.frames(i0, i1), host=host)


async def post_body(
    port: int, body: bytes, host: str = "127.0.0.1", path: str = "/api/v1/write"
) -> int:
    import httpx

    async with httpx.AsyncClient(timeout=30) as client:
        r = await client.post(
            f"http://{host}:{port}{path}",
            content=body,
            headers={
                "Content-Type": "application/x-protobuf",
                "Content-Encoding": "snappy",
                "X-Prometheus-Remote-Write-Version": "0.1.0",
            },
        )
        return r.status_code


def grid_samples(values: np.ndarray, i0: int, i1: int, sender: RemoteWriteSender) -> list[tuple[float, int]]:
    """Convenience for hand-built series: values[i0..i1] on the sender grid."""
    return [(float(values[i]), sender.ts_ms(i)) for i in range(i0, i1 + 1)]
