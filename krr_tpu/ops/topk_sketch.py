"""Exact streaming quantile sketch for high percentiles: per-row top-K values.

The reference computes one percentile per container (default p99,
`/root/reference/robusta_krr/strategies/simple.py:31-36`). For q ≥ ~97 the
rank-from-the-top of that percentile is a small, *a-priori bounded* number
``K`` — e.g. 1,211 for p99 over 7 d @ 5 s — so keeping each row's top-K
samples is a fixed-size, **exact** sketch:

* streaming: fold a time chunk with ``top_k(concat(state, chunk))``,
* mergeable: ``merge(a, b) = top_k(concat)`` is associative and commutative
  (the top-K of a union is contained in the union of top-Ks),
* query: the percentile at rank ``r`` from the top is ``state[:, r]``.

Compared to the log-bucket digest (`krr_tpu.ops.digest`) this has **zero
error** and roughly half the cost (one single-key sort per chunk instead of
two), but only answers quantiles whose top-rank fits in ``K`` — the tdigest
strategy auto-selects it when the configured percentile qualifies and falls
back to the histogram digest otherwise.

TPU notes: ``lax.top_k`` lowers to a fast single-operand sort + slice; the
state rides along the scan carry, so HBM traffic per chunk is ``C + K``
values. ``K`` is rounded up to the 128-lane boundary.
"""

from __future__ import annotations

import math
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp


class TopKSketch(NamedTuple):
    """Per-row exact top-K state — a pytree, shardable and tree-mergeable."""

    values: jax.Array  # [N, K] float32, descending; -inf beyond the real samples
    total: jax.Array  # [N] float32 total (valid) sample count


def required_k(capacity: int, q: float) -> int:
    """Smallest K that answers percentile ``q`` for any row with up to
    ``capacity`` samples, with the reference's rank semantics
    (``index = floor((n - 1) * q / 100)`` into the ascending sort), rounded up
    to the 128-lane boundary."""
    if capacity <= 0:
        return 128
    n = capacity
    rank_from_top = (n - 1) - math.floor((n - 1) * q / 100.0)
    return ((rank_from_top + 1) + 127) // 128 * 128


def empty(num_rows: int, k: int) -> TopKSketch:
    return TopKSketch(
        values=jnp.full((num_rows, k), -jnp.inf, dtype=jnp.float32),
        total=jnp.zeros((num_rows,), dtype=jnp.float32),
    )


def add_chunk(sketch: TopKSketch, values: jax.Array, valid: jax.Array) -> TopKSketch:
    """Fold one ``[N, Tc]`` time chunk (with validity mask) into the sketch."""
    k = sketch.values.shape[1]
    masked = jnp.where(valid, values, -jnp.inf)
    top, _ = jax.lax.top_k(jnp.concatenate([sketch.values, masked], axis=1), k)
    return TopKSketch(values=top, total=sketch.total + jnp.sum(valid, axis=1).astype(jnp.float32))


def merge(a: TopKSketch, b: TopKSketch) -> TopKSketch:
    """Associative, commutative merge — also the cross-device collective body."""
    k = a.values.shape[1]
    top, _ = jax.lax.top_k(jnp.concatenate([a.values, b.values], axis=1), k)
    return TopKSketch(values=top, total=a.total + b.total)


@jax.jit
def percentile(sketch: TopKSketch, q: jax.Array | float) -> jax.Array:
    """Per-row q-th percentile with reference rank semantics. Exact whenever
    the rank-from-top fits in K (guaranteed by ``required_k``); NaN for empty
    rows — and NaN, not a silently-wrong clipped value, for rows whose rank
    falls outside the sketch (a caller-chosen K that is too small for this
    q/total combination)."""
    k = sketch.values.shape[1]
    rank_bottom = jnp.floor(jnp.maximum(sketch.total - 1.0, 0.0) * jnp.float32(q) / 100.0)
    rank_top = jnp.maximum(sketch.total - 1.0, 0.0) - rank_bottom
    idx = jnp.clip(rank_top.astype(jnp.int32), 0, k - 1)
    out = jnp.take_along_axis(sketch.values, idx[:, None], axis=1)[:, 0]
    answerable = (sketch.total > 0) & (rank_top < k)
    return jnp.where(answerable, out, jnp.nan)


@partial(jax.jit, static_argnames=("k", "chunk_size"))
def build_from_packed(
    values: jax.Array,
    counts: jax.Array,
    k: int,
    chunk_size: int = 8192,
    time_offset: "int | jax.Array" = 0,
) -> TopKSketch:
    """Build the sketch from a packed ``[N, T]`` array by scanning time chunks.

    Shares the chunking/validity driver (`krr_tpu.ops.chunked`) with the
    digest build; chunked == one-shot because the merge is exact.
    """
    from krr_tpu.ops.chunked import scan_time_chunks

    n = values.shape[0]
    return scan_time_chunks(values, counts, empty(n, k), add_chunk, chunk_size, time_offset)


def build_from_host(
    values: "np.ndarray",
    counts: "np.ndarray",
    k: int,
    chunk_size: int = 8192,
    time_offset: int = 0,
    sharding=None,
) -> TopKSketch:
    """Build the sketch from a **host-resident** ``[N, T]`` array, streaming
    time chunks to the device — bit-identical to :func:`build_from_packed`
    with device memory bounded by the ``[N, K]`` state plus ~2 chunks."""
    from krr_tpu.ops.chunked import stream_host_chunks

    return stream_host_chunks(
        values, counts, empty(values.shape[0], k), add_chunk, chunk_size, time_offset, sharding=sharding
    )
