from krr_tpu.formatters.base import BaseFormatter

__all__ = ["BaseFormatter"]
