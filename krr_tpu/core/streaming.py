"""Incremental digest state: streaming, multi-source merge, checkpoint/resume.

The reference is stateless end-to-end (SURVEY.md §5 "checkpoint/resume:
absent"); its only knob for long histories is a coarser Prometheus step. The
digest's associative merge gives us something stronger for free: persist each
container's digest, and

* **streaming** = merge the new window's digest into the stored one (no
  re-fetch of old history);
* **multi-source** = scan each Prometheus source (cluster, federated shard,
  region) separately against the same store — merges commute, order doesn't
  matter (BASELINE.md config 5);
* **checkpoint/resume** = the store *is* the checkpoint; a killed run loses
  only the unmerged window.

State lives in one ``.npz`` (bucket counts / totals / peaks / memory peaks)
plus row keys, keyed by the object identity string, so fleets can grow,
shrink, and reorder between scans.
"""

from __future__ import annotations

import contextlib
import fcntl
import json
import os
import tempfile
from dataclasses import dataclass, field
from typing import Iterator, Optional

import numpy as np

from krr_tpu.models.objects import K8sObjectData
from krr_tpu.ops.digest import DigestSpec


def object_key(obj: K8sObjectData) -> str:
    return f"{obj.cluster or ''}/{obj.namespace}/{obj.name}/{obj.container}/{obj.kind or ''}"


def split_object_key(key: str) -> "tuple[Optional[str], str, str, str, Optional[str]]":
    """The inverse of :func:`object_key`: ``(cluster, namespace, name,
    container, kind)`` with empty segments back to None. Splits from the
    RIGHT: only the cluster segment can itself contain ``/`` (EKS context
    names are ARNs like ``arn:aws:eks:...:cluster/prod``), and a left split
    would shift every field. Lives beside the forward map so every consumer
    (the /history filters, the diff renderer) parses identically."""
    parts = key.rsplit("/", 4)
    if len(parts) < 5:
        parts = [""] * (5 - len(parts)) + parts
    cluster, namespace, name, container, kind = parts
    return cluster or None, namespace, name, container, kind or None


def filter_key_indices(
    keys,
    namespaces=(),
    workloads=(),
    containers=(),
) -> "list[int]":
    """Row indices of ``keys`` (object-key strings, the store/snapshot key
    table) whose namespace / workload name / container match the filter
    sets (an empty set is a wildcard) — the serve read path's filter
    pushdown: ``GET /recommendations?namespace=…`` resolves indices against
    this key table and materializes ONLY the selected rows, instead of
    iterating every rendered scan object per request. Parses through
    :func:`split_object_key` so the HTTP filters and every other key
    consumer (/history, the diff renderer) agree on the key grammar."""
    if not (namespaces or workloads or containers):
        return list(range(len(keys)))
    out: list[int] = []
    for i, key in enumerate(keys):
        _cluster, namespace, name, container, _kind = split_object_key(key)
        if namespaces and namespace not in namespaces:
            continue
        if workloads and name not in workloads:
            continue
        if containers and container not in containers:
            continue
        out.append(i)
    return out


class FsOps:
    """Every durability-critical filesystem syscall behind one injectable
    seam. The durable store (`krr_tpu.core.durastore`), :func:`atomic_write`,
    and the WAL appends all route their fsync/rename/append/write calls
    through an ``FsOps`` instance, so fault-injection harnesses (the chaos
    fakes' disk-fault injector, the crash-point matrix in the durability
    tests) can script ENOSPC/EIO — or a simulated crash — at any single
    fault point without monkeypatching ``os``."""

    def write(self, f, data: bytes) -> None:
        f.write(data)

    def append(self, f, data: bytes) -> None:
        """Same syscall as :meth:`write`, named separately so WAL appends
        are their own fault point (scripts can fail the per-tick delta
        append without also failing base-snapshot writes)."""
        f.write(data)

    def fsync(self, f) -> None:
        os.fsync(f.fileno())

    def replace(self, src: str, dst: str) -> None:
        os.replace(src, dst)

    def fsync_dir(self, path: str) -> None:
        """fsync a DIRECTORY: makes renames/creates/unlinks inside it
        durable. Without it, a crash shortly after ``os.replace`` can lose
        the rename itself — the old name comes back after the reboot even
        though the replace "succeeded"."""
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def truncate(self, f, size: int) -> None:
        f.truncate(size)


#: The process-default ops. Durable-store instances carry their own
#: reference so tests can fault one store without touching the process.
FS = FsOps()


@contextlib.contextmanager
def atomic_write(path: str, mode: str = "wb", fs: Optional[FsOps] = None) -> Iterator:
    """Crash-safe file replacement: write a temp file in the target's
    directory, FSYNC it, atomically rename over ``path``, then FSYNC the
    parent directory. The file fsync before the rename is load-bearing:
    rename-only guarantees the old OR new *name*, but a crash shortly after
    the rename can land the new name on unwritten data — a truncated
    store/journal, which is strictly worse than the stale-but-complete file
    the rename was meant to preserve. The directory fsync after it makes
    the RENAME itself durable: until the parent's metadata hits disk, a
    crash can resurrect the old file even though ``os.replace`` returned.
    Shared by the digest store (manifest + legacy snapshot), the serve
    window cursor (inside the store's save), and the recommendation
    journal."""
    fs = fs or FS
    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, mode) as f:
            yield f
            f.flush()
            fs.fsync(f)
        fs.replace(tmp, path)
        fs.fsync_dir(directory)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def flatnonzero_f32(counts: np.ndarray) -> np.ndarray:
    """``np.flatnonzero`` over a float32 matrix via its int32 bit view —
    ~3x faster at WAL-record scale (the comparison runs on integers and
    skips float semantics). Only divergence from the float comparison:
    ``-0.0`` reads as occupied; digest counts are sums of non-negative
    values, and an explicit ``-0.0`` entry replays to bit-identical state
    anyway (x + -0.0 == x, 0.0 + -0.0 == +0.0)."""
    return np.flatnonzero(np.ascontiguousarray(counts).view(np.int32))


def csr_encode(counts: np.ndarray, num_buckets: int, rows: int, flat: Optional[np.ndarray] = None):
    """Sparse (CSR) encoding of a ``[rows x num_buckets]`` count matrix —
    ``(vals, cols, indptr)`` with the same dtypes the legacy ``.npz``
    snapshot format uses (byte-compatibility is load-bearing: the sharded
    base snapshots and the legacy single-file format share this encoder).
    ``flat`` injects a precomputed occupied-index array (the WAL encoder
    passes :func:`flatnonzero_f32`'s); default is the exact float scan the
    legacy format has always used."""
    if flat is None:
        flat = np.flatnonzero(counts)
    vals = counts.ravel()[flat]
    col_dtype = np.uint16 if num_buckets <= np.iinfo(np.uint16).max else np.int32
    cols = (flat % num_buckets).astype(col_dtype)
    per_row = np.bincount(flat // num_buckets, minlength=rows)
    indptr = np.zeros(rows + 1, dtype=np.int64)
    np.cumsum(per_row, out=indptr[1:])
    return vals, cols, indptr


def csr_decode(vals, cols, indptr, rows: int, num_buckets: int) -> np.ndarray:
    """Inverse of :func:`csr_encode` back to the dense float32 matrix."""
    cols = np.asarray(cols).astype(np.int64, copy=False)
    counts = np.zeros((rows, num_buckets), dtype=np.float32)
    row_of = np.repeat(np.arange(rows, dtype=np.int64), np.diff(indptr))
    counts.ravel()[row_of * num_buckets + cols] = vals
    return counts


@dataclass
class DigestStore:
    """Host-side persistent digest state for a fleet."""

    spec: DigestSpec
    keys: list[str] = field(default_factory=list)
    cpu_counts: np.ndarray = None  # [N, B] float32
    cpu_total: np.ndarray = None  # [N] float32
    cpu_peak: np.ndarray = None  # [N] float32 (-inf when empty)
    mem_total: np.ndarray = None  # [N] float32
    mem_peak: np.ndarray = None  # [N] float32, in MB (-inf when empty)
    #: Caller-owned JSON-serializable annotations persisted INSIDE the same
    #: atomic save as the arrays (the serve scheduler keeps its window
    #: cursor here — a sidecar file could desync from the store on a crash
    #: between two writes, which is exactly a lost or double-counted
    #: window). Round-trips through save/load; absent in legacy files.
    extra_meta: dict = field(default_factory=dict)

    def __post_init__(self) -> None:
        n, b = len(self.keys), self.spec.num_buckets
        if self.cpu_counts is None:
            self.cpu_counts = np.zeros((n, b), dtype=np.float32)
            self.cpu_total = np.zeros(n, dtype=np.float32)
            self.cpu_peak = np.full(n, -np.inf, dtype=np.float32)
            self.mem_total = np.zeros(n, dtype=np.float32)
            self.mem_peak = np.full(n, -np.inf, dtype=np.float32)
        self._index = {key: i for i, key in enumerate(self.keys)}
        #: Delta capture for the durable WAL (`krr_tpu.core.durastore`):
        #: when enabled, every mutation appends a replayable op — ("fold",
        #: keys, window arrays), ("grow", keys), ("drop", keys) — so a
        #: persist can append ONLY this tick's contribution instead of
        #: rewriting the whole state. Off by default: untracked consumers
        #: (cold CLI scans) must not accumulate window arrays forever.
        self.track_deltas = False
        #: When True, whole-store folds capture their key list EXPLICITLY
        #: instead of eliding it. The elision is only sound when the replay
        #: target holds the identical keys by induction (WAL recovery of
        #: the same store); a capture destined for a DIFFERENT store — a
        #: federation shard streaming its delta ops into the aggregator's
        #: merged fleet store (`krr_tpu.federation`) — must carry keys so
        #: the ops scatter onto the right rows of a store that also holds
        #: other shards' keys.
        self.capture_full_keys = False
        self._pending_ops: list = []

    # ------------------------------------------------------------------ merge
    def _ensure_rows(self, keys: list[str]) -> np.ndarray:
        """Indices for ``keys``, growing the store for unseen objects. A key
        repeated within one call (duplicate-object windows) must grow ONE
        row, not one per occurrence — the dedup here keeps the index and the
        row arrays consistent."""
        new = list(dict.fromkeys(key for key in keys if key not in self._index))
        if new:
            grow = len(new)
            if self.cpu_counts.shape[0] == 0:
                # Fresh store (every first scan at fleet scale): plain zeros —
                # vstack against the empty matrix would pay a full extra copy
                # of the [N x B] state (~0.7 s at 100k x 2560).
                self.cpu_counts = np.zeros((grow, self.spec.num_buckets), np.float32)
            else:
                self.cpu_counts = np.vstack(
                    [self.cpu_counts, np.zeros((grow, self.spec.num_buckets), np.float32)]
                )
            self.cpu_total = np.concatenate([self.cpu_total, np.zeros(grow, np.float32)])
            self.cpu_peak = np.concatenate([self.cpu_peak, np.full(grow, -np.inf, np.float32)])
            self.mem_total = np.concatenate([self.mem_total, np.zeros(grow, np.float32)])
            self.mem_peak = np.concatenate([self.mem_peak, np.full(grow, -np.inf, np.float32)])
            for key in new:
                self._index[key] = len(self.keys)
                self.keys.append(key)
        return np.asarray([self._index[key] for key in keys], dtype=np.int64)

    def merge_window(
        self,
        keys: list[str],
        cpu_counts: np.ndarray,
        cpu_total: np.ndarray,
        cpu_peak: np.ndarray,
        mem_total: np.ndarray,
        mem_peak: np.ndarray,
    ) -> np.ndarray:
        """Fold one scanned window (any source, any order) into the store;
        returns the store row index for each input key."""
        # Checked BEFORE _ensure_rows grows the store: a whole-store fold
        # (the seasoned serve tick — every resident row, in row order, no
        # new keys) can elide its key list from the delta capture, because
        # replay re-derives it from the store, which by induction holds the
        # identical keys at that point. A growing window never elides.
        whole = (
            self.track_deltas
            and not self.capture_full_keys
            and len(keys) == len(self.keys)
            and list(keys) == self.keys
        )
        rows = self._ensure_rows(keys)

        def f32(a: np.ndarray) -> np.ndarray:
            return np.asarray(a).astype(np.float32, copy=False)  # no copy when already f32

        if self.track_deltas:
            # Capture the window's CONTRIBUTION (not the resulting rows):
            # replaying captured windows in order re-applies the same exact
            # integer adds and peak maxes, so WAL replay reconstructs the
            # store bit-identically. References, not copies — callers never
            # mutate a window after folding it.
            self._pending_ops.append(
                (
                    "fold",
                    None if whole else list(keys),
                    f32(cpu_counts),
                    f32(cpu_total),
                    f32(cpu_peak),
                    f32(mem_total),
                    f32(mem_peak),
                )
            )
        window = self._contiguous_slice(rows, len(self.keys))
        if window is not None:
            # The common case — a fleet scanned in a stable order lands on a
            # contiguous row range (fresh stores exactly so): slice ops run
            # at memory bandwidth, ~2.5x faster than the buffered scatter on
            # a [100k x 2560] fold (and ~9x faster than fancy-index +=).
            self.cpu_counts[window] += f32(cpu_counts)
            self.cpu_total[window] += f32(cpu_total)
            np.maximum(self.cpu_peak[window], f32(cpu_peak), out=self.cpu_peak[window])
            self.mem_total[window] += f32(mem_total)
            np.maximum(self.mem_peak[window], f32(mem_peak), out=self.mem_peak[window])
        else:  # arbitrary row order / duplicate keys: accumulate via scatter
            np.add.at(self.cpu_counts, rows, f32(cpu_counts))
            np.add.at(self.cpu_total, rows, f32(cpu_total))
            np.maximum.at(self.cpu_peak, rows, f32(cpu_peak))
            np.add.at(self.mem_total, rows, f32(mem_total))
            np.maximum.at(self.mem_peak, rows, f32(mem_peak))
        return rows

    def merge_window_csr(
        self,
        keys: list[str],
        vals: np.ndarray,
        cols: np.ndarray,
        indptr: np.ndarray,
        cpu_total: np.ndarray,
        cpu_peak: np.ndarray,
        mem_total: np.ndarray,
        mem_peak: np.ndarray,
    ) -> np.ndarray:
        """Sparse twin of :meth:`merge_window`: fold a CSR-encoded window
        (the WAL/federation record form) WITHOUT materializing the dense
        [rows x num_buckets] matrix — the replay hot path for keyed records
        (`krr_tpu.core.durastore.apply_ops`). At delta occupancy the scatter
        touches ~1/250th of the cells the dense fold would, and the delta
        capture stays in CSR form (``fold_csr`` — identical WAL bytes), so
        an aggregator replaying many shards' records never pins dense
        windows. Bit-exactness: the scatter applies the same float32 adds
        to the same cells in the same row-major order the dense fold would
        (untouched cells would have added +0.0 — a no-op: digest counts
        are sums of non-negative values, so ``-0.0`` cannot occur)."""

        def f32(a: np.ndarray) -> np.ndarray:
            return np.asarray(a).astype(np.float32, copy=False)

        rows = self._ensure_rows(keys)
        cpu_total, cpu_peak = f32(cpu_total), f32(cpu_peak)
        mem_total, mem_peak = f32(mem_total), f32(mem_peak)
        if self.track_deltas:
            self._pending_ops.append(
                ("fold_csr", list(keys), vals, cols, indptr,
                 cpu_total, cpu_peak, mem_total, mem_peak)
            )
        cols64 = np.asarray(cols).astype(np.int64, copy=False)
        row_of = np.repeat(rows, np.diff(indptr))
        np.add.at(
            self.cpu_counts.ravel(), row_of * self.spec.num_buckets + cols64, vals
        )
        np.add.at(self.cpu_total, rows, cpu_total)
        np.maximum.at(self.cpu_peak, rows, cpu_peak)
        np.add.at(self.mem_total, rows, mem_total)
        np.maximum.at(self.mem_peak, rows, mem_peak)
        return rows

    def fold_fleet(self, fleet, mem_scale: float = 1.0) -> np.ndarray:
        """Delta-window fold entry point: merge one fetched (digested) window
        into the store. The tdigest ``state_path`` merge and the serve
        scheduler's per-tick fold share this conversion — ``DigestedFleet``
        memory peaks arrive in bytes while the store keeps MB, so callers
        pass ``mem_scale`` (the strategy layer's MEMORY_SCALE). Returns the
        store row index for each fleet object, for the follow-up quantile
        query. Exactness contract: digest bucket counts are integer-valued,
        so folding windows one at a time accumulates bit-identical state to
        folding their union in one window."""
        keys = [object_key(obj) for obj in fleet.objects]
        mem_peak = np.where(np.isfinite(fleet.mem_peak), fleet.mem_peak / mem_scale, -np.inf)
        return self.merge_window(
            keys, fleet.cpu_counts, fleet.cpu_total, fleet.cpu_peak, fleet.mem_total, mem_peak
        )

    def __contains__(self, key: str) -> bool:
        return key in self._index

    def rows_for(self, keys: list[str]) -> np.ndarray:
        """Store row indices for ``keys``, growing empty rows for unseen
        objects (which then query as NaN → UNKNOWN scans) — the serve
        resume path's query-without-fold: recommendations straight from the
        resident state, no new window."""
        if self.track_deltas:
            missing = list(dict.fromkeys(k for k in keys if k not in self._index))
            if missing:
                self._pending_ops.append(("grow", missing))
        return self._ensure_rows(keys)

    def compact(self, keep: "frozenset[str] | set[str]") -> int:
        """Drop rows whose key is not in ``keep``, returning the number
        dropped. A long-lived server re-discovers the fleet on a slow
        cadence; without compaction, workload churn would grow the store
        (and its [N x B] count matrix) without bound. Row indices shift —
        callers re-derive them via the next ``fold_fleet``/``merge_window``."""
        mask = np.fromiter((key in keep for key in self.keys), dtype=bool, count=len(self.keys))
        dropped = int(len(self.keys) - mask.sum())
        if not dropped:
            return 0
        if self.track_deltas:
            self._pending_ops.append(
                ("drop", [key for key, m in zip(self.keys, mask) if not m])
            )
        self.keys = [key for key, m in zip(self.keys, mask) if m]
        self.cpu_counts = self.cpu_counts[mask]
        self.cpu_total = self.cpu_total[mask]
        self.cpu_peak = self.cpu_peak[mask]
        self.mem_total = self.mem_total[mask]
        self.mem_peak = self.mem_peak[mask]
        self._index = {key: i for i, key in enumerate(self.keys)}
        return dropped

    @property
    def nbytes(self) -> int:
        """Resident size of the row arrays (the serve ``/metrics`` gauge)."""
        return sum(
            a.nbytes
            for a in (self.cpu_counts, self.cpu_total, self.cpu_peak, self.mem_total, self.mem_peak)
        )

    # ---------------------------------------------------------- delta capture
    def pending_ops(self) -> list:
        """Snapshot of the captured (unpersisted) mutation ops, oldest
        first. The durable store encodes these into one WAL record; pass
        the snapshot's length to :meth:`clear_pending` only AFTER the
        record is durably on disk — a failed persist keeps the ops queued
        so the next tick's record carries both ticks' deltas."""
        return list(self._pending_ops)

    def clear_pending(self, count: int) -> None:
        del self._pending_ops[:count]

    def compact_pending(self) -> None:
        """Re-encode queued dense fold windows as sparse CSR in place. The
        capture normally holds a REFERENCE to each tick's dense
        [N x num_buckets] window (free on the happy path — the array lives
        until the tick ends anyway, and ``save_delta`` drains it); under a
        SUSTAINED persist failure the backlog would otherwise pin one dense
        matrix per tick (~1 GB each at 100k rows) until the process OOMs —
        turning a survivable disk-full into a kill. Sparse form is ~250x
        smaller at delta-window occupancy and encodes to the identical WAL
        bytes (the encoder accepts both shapes)."""
        for i, op in enumerate(self._pending_ops):
            if op[0] != "fold":
                continue
            _, keys, cpu_counts, cpu_total, cpu_peak, mem_total, mem_peak = op
            vals, cols, indptr = csr_encode(
                cpu_counts, self.spec.num_buckets, len(cpu_total),
                flat=flatnonzero_f32(cpu_counts),
            )
            self._pending_ops[i] = (
                "fold_csr", keys, vals, cols, indptr,
                cpu_total, cpu_peak, mem_total, mem_peak,
            )

    def row_slice(self, lo: int, hi: int) -> "DigestStore":
        """A store VIEW over rows ``[lo, hi)`` (shared array memory) — what
        the durable store writes per-shard base snapshots from."""
        return DigestStore(
            spec=self.spec,
            keys=self.keys[lo:hi],
            cpu_counts=self.cpu_counts[lo:hi],
            cpu_total=self.cpu_total[lo:hi],
            cpu_peak=self.cpu_peak[lo:hi],
            mem_total=self.mem_total[lo:hi],
            mem_peak=self.mem_peak[lo:hi],
        )

    # -------------------------------------------------------------- quantiles
    @staticmethod
    def _contiguous_slice(rows: np.ndarray, n: int) -> Optional[slice]:
        """The equivalent ``slice`` when ``rows`` is a contiguous ascending
        IN-BOUNDS range over an ``n``-row axis, else None. The bounds check
        matters: out-of-range fancy indices raise IndexError, and the slice
        path must not silently truncate instead. One helper for both the
        merge fast path and the query view so the two cannot drift."""
        if rows.size == 0 or rows[0] < 0 or rows[-1] >= n:
            return None
        if np.array_equal(rows, np.arange(rows[0], rows[0] + rows.size)):
            return slice(int(rows[0]), int(rows[0]) + rows.size)
        return None

    def _take(self, rows: np.ndarray, *arrays: np.ndarray) -> list[np.ndarray]:
        """``[a[rows] for a in arrays]``, but zero-copy VIEWS when ``rows`` is
        a contiguous ascending range — the overwhelmingly common whole-fleet
        query, where the fancy-index copy of the [N x B] count matrix costs
        4.5 s at 100k x 2560 (measured) and the view costs nothing. One
        contiguity check covers every array."""
        rows = np.asarray(rows)
        window = self._contiguous_slice(rows, len(self.keys))
        if window is not None:
            return [a[window] for a in arrays]
        return [a[rows] for a in arrays]

    def cpu_percentile(self, rows: np.ndarray, q: float) -> np.ndarray:
        """Quantile estimate from merged counts — the shared host-numpy query
        (`krr_tpu.ops.digest.percentile_host`; that docstring records why the
        host, not the device, serves host-resident digests). NaN where no data."""
        from krr_tpu.ops.digest import percentile_host

        counts, total, peak = self._take(rows, self.cpu_counts, self.cpu_total, self.cpu_peak)
        return percentile_host(self.spec, counts, total, peak, q)

    def memory_peak(self, rows: np.ndarray) -> np.ndarray:
        total, peak = self._take(rows, self.mem_total, self.mem_peak)
        return np.where(total > 0, peak, np.nan).astype(np.float32)

    def query_recommendation(self, rows: np.ndarray, q: float) -> tuple[np.ndarray, np.ndarray]:
        """(CPU percentile, memory peak MB) for ``rows`` — THE digested-store
        recommendation query, shared by ``TDigestStrategy.run_digested``, the
        serve scheduler's publish path, and the journal/diff tooling, so no
        two consumers can drift apart on what a recommendation is."""
        return np.asarray(self.cpu_percentile(rows, q)), np.asarray(self.memory_peak(rows))

    # ------------------------------------------------------------ persistence
    #
    # On-disk format: the count matrix is stored SPARSELY (CSR — concatenated
    # per-row occupied buckets) and UNCOMPRESSED. The dense state is mostly
    # zeros (a series' samples occupy tens of its 2,560 buckets), and pushing
    # the dense 1 GB through zlib cost ~5 s each way at 100k rows (measured
    # round 3); the sparse extraction is one pass over the matrix (~1.5 s)
    # and the write/read run at disk speed. Dense legacy files still load.

    def write_npz(self, f) -> None:
        """The raw ``.npz`` snapshot writer — shared by the legacy
        single-file :meth:`save` and the sharded base-snapshot writer
        (`krr_tpu.core.durastore`), so both formats stay byte-compatible
        down to the CSR dtypes."""
        meta = {
            "gamma": self.spec.gamma,
            "min_value": self.spec.min_value,
            "num_buckets": self.spec.num_buckets,
        }
        if self.extra_meta:
            meta["extra"] = self.extra_meta
        vals, cols, indptr = csr_encode(self.cpu_counts, self.spec.num_buckets, len(self.keys))
        np.savez(
            f,
            meta=json.dumps(meta),
            keys=np.asarray(self.keys),
            csr_vals=vals,
            csr_cols=cols,
            csr_indptr=indptr,
            cpu_total=self.cpu_total,
            cpu_peak=self.cpu_peak,
            mem_total=self.mem_total,
            mem_peak=self.mem_peak,
        )

    def save(self, path: str) -> None:
        """Atomic write (tmp + fsync + rename + parent-dir fsync via
        :func:`atomic_write`): a crash at any point keeps a complete file —
        old state before the rename, fully-written new state after it,
        never a truncated one. This is the LEGACY single-file format
        (``--store_format legacy``); the sharded state-directory format
        lives in `krr_tpu.core.durastore`."""
        with atomic_write(path) as f:
            self.write_npz(f)

    @classmethod
    def load(cls, path) -> "DigestStore":
        """Load a single-file snapshot — a path or an open binary file
        object (the sharded store loads its base shards through here)."""
        with np.load(path, allow_pickle=False) as data:
            meta = json.loads(str(data["meta"]))
            spec = DigestSpec(gamma=meta["gamma"], min_value=meta["min_value"], num_buckets=meta["num_buckets"])
            keys = [str(k) for k in data["keys"]]
            if "cpu_counts" in data:  # legacy dense (zlib) format
                counts = data["cpu_counts"]
            else:
                counts = csr_decode(
                    data["csr_vals"], data["csr_cols"], data["csr_indptr"],
                    len(keys), spec.num_buckets,
                )
            return cls(
                spec=spec,
                keys=keys,
                cpu_counts=counts,
                cpu_total=data["cpu_total"],
                cpu_peak=data["cpu_peak"],
                mem_total=data["mem_total"],
                mem_peak=data["mem_peak"],
                extra_meta=meta.get("extra", {}),
            )

    @staticmethod
    @contextlib.contextmanager
    def locked(path: str) -> Iterator[None]:
        """Advisory exclusive lock for one load-merge-save cycle, so concurrent
        multi-source scans against the same state serialize instead of the
        last save silently discarding the other's merge. The lock file is
        REMOVED on release (state directories used to accumulate ``.lock``
        litter forever); the open/flock/stat loop handles the classic
        unlink race — a waiter that acquired the flock on an already-
        unlinked inode notices the path no longer names its inode and
        retries on the fresh lock file."""
        lock_path = path + ".lock"
        while True:
            lock_file = open(lock_path, "a")
            fcntl.flock(lock_file, fcntl.LOCK_EX)
            try:
                if os.path.samestat(os.fstat(lock_file.fileno()), os.stat(lock_path)):
                    break
            except OSError:
                pass  # unlinked under us — retry on the recreated file
            lock_file.close()
        try:
            yield
        finally:
            # Unlink BEFORE releasing: we still hold the exclusive lock, so
            # no other holder exists; blocked waiters detect the swap above.
            with contextlib.suppress(OSError):
                os.unlink(lock_path)
            fcntl.flock(lock_file, fcntl.LOCK_UN)
            lock_file.close()

    @classmethod
    def open_or_create(cls, path: Optional[str], spec: DigestSpec) -> "DigestStore":
        if path and os.path.isdir(path):
            # A sharded state DIRECTORY (`krr_tpu.core.durastore`): recover
            # it (checksums verified, WAL replayed) and hand back the
            # reconstructed in-memory store — one-shot readers and the
            # tdigest CLI then see a serve-written directory transparently.
            from krr_tpu.core.durastore import DurableStore

            durable = DurableStore.open(path, spec)
            durable.close()
            # This handle has no persistence engine draining the capture:
            # a long-lived reader folding into it must not pin window
            # arrays forever (the track_deltas contract).
            durable.store.track_deltas = False
            durable.store._pending_ops.clear()
            return durable.store
        if path and os.path.exists(path):
            try:
                store = cls.load(path)
            except Exception as e:  # BadZipFile / KeyError / EOFError / ValueError
                raise ValueError(
                    f"digest state at {path} is unreadable ({type(e).__name__}: {e}); "
                    f"delete the file to start fresh"
                ) from e
            if (store.spec.gamma, store.spec.min_value, store.spec.num_buckets) != (
                spec.gamma,
                spec.min_value,
                spec.num_buckets,
            ):
                raise ValueError(
                    f"digest state at {path} was built with spec {store.spec}, "
                    f"incompatible with requested {spec}; delete the state file or match the settings"
                )
            return store
        return cls(spec=spec)
