"""Rich-table formatter — the default human-facing output.

Layout-compatible with the reference's table
(`/root/reference/robusta_krr/formatters/table.py:45-92`): rows grouped by
(cluster, namespace, name) with repeated fields blanked, each cell rendered as
``current -> recommended`` in the cell severity's color, values humanized to 4
significant digits, ``none`` for absent values and ``?`` for unknown.
"""

from __future__ import annotations

import itertools
from typing import Optional

from rich.table import Table

from krr_tpu.formatters.base import BaseFormatter
from krr_tpu.models.allocations import RecommendationValue, ResourceType
from krr_tpu.models.result import ResourceScan, Result
from krr_tpu.utils import resource_units

NONE_LITERAL = "none"
NAN_LITERAL = "?"
PRECISION = 4


def _humanize(value: RecommendationValue, precision: Optional[int] = None) -> str:
    if value is None:
        return NONE_LITERAL
    if isinstance(value, str):
        return NAN_LITERAL
    return resource_units.format(value, precision)


class TableFormatter(BaseFormatter):
    """Formatter for rich text-table output."""

    __display_name__ = "table"

    def _format_cell(self, scan: ResourceScan, resource: ResourceType, selector: str) -> str:
        allocated = getattr(scan.object.allocations, selector)[resource]
        recommended = getattr(scan.recommended, selector)[resource]
        color = recommended.severity.color
        return f"[{color}]{_humanize(allocated)} -> {_humanize(recommended.value, PRECISION)}[/{color}]"

    def format(self, result: Result) -> Table:
        table = Table(show_header=True, header_style="bold magenta", title=f"Scan result ({result.score} points)")
        table.add_column("Number", justify="right", no_wrap=True)
        for column in ("Cluster", "Namespace", "Name", "Pods", "Type", "Container"):
            table.add_column(column, style="cyan")
        for resource in ResourceType:
            table.add_column(f"{resource.name} Requests")
            table.add_column(f"{resource.name} Limits")

        group_key = lambda pair: (pair[1].object.cluster, pair[1].object.namespace, pair[1].object.name)
        for _, group in itertools.groupby(enumerate(result.scans), key=group_key):
            rows = list(group)
            for j, (i, scan) in enumerate(rows):
                first, last = j == 0, j == len(rows) - 1
                table.add_row(
                    f"[{scan.severity.color}]{i + 1}.[/{scan.severity.color}]",
                    (scan.object.cluster or "") if first else "",
                    scan.object.namespace if first else "",
                    scan.object.name if first else "",
                    str(len(scan.object.pods)) if first else "",
                    (scan.object.kind or "") if first else "",
                    scan.object.container,
                    *[
                        self._format_cell(scan, resource, selector)
                        for resource in ResourceType
                        for selector in ("requests", "limits")
                    ],
                    end_section=last,
                )
        return table
