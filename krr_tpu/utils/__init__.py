from krr_tpu.utils import resource_units
from krr_tpu.utils.logging import KrrLogger, NULL_LOGGER
from krr_tpu.utils.ttl_cache import TTLCache

__all__ = ["resource_units", "KrrLogger", "NULL_LOGGER", "TTLCache"]
