"""Multi-device tests on the virtual 8-device CPU mesh (SURVEY.md §4 item 4)."""

import jax
import numpy as np
import pytest

from krr_tpu.ops import digest as digest_ops
from krr_tpu.ops.digest import DigestSpec
from krr_tpu.ops.quantile import masked_max, masked_percentile
from krr_tpu.parallel import make_mesh, sharded_fleet_digest, sharded_percentile

SPEC = DigestSpec(gamma=1.01, min_value=1e-7, num_buckets=2560)


@pytest.fixture(scope="module")
def fleet(request):
    rng = np.random.default_rng(99)
    n, t = 37, 1500  # deliberately not divisible by mesh axes
    values = rng.gamma(2.0, 0.05, size=(n, t))
    counts = rng.integers(0, t + 1, size=n).astype(np.int32)
    counts[0] = 0
    counts[1] = t
    return values, counts


def test_devices_available():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2), (2, 4), (1, 8)])
def test_sharded_digest_matches_single_device(fleet, mesh_shape):
    values, counts = fleet
    mesh = make_mesh(data=mesh_shape[0], time=mesh_shape[1])

    single = digest_ops.build_from_packed(SPEC, values.astype(np.float32), counts, chunk_size=512)
    sharded, real_rows = sharded_fleet_digest(SPEC, values, counts, mesh, chunk_size=512)

    assert real_rows == values.shape[0]
    np.testing.assert_array_equal(np.asarray(sharded.counts)[:real_rows], np.asarray(single.counts))
    np.testing.assert_array_equal(np.asarray(sharded.total)[:real_rows], np.asarray(single.total))
    np.testing.assert_array_equal(np.asarray(sharded.peak)[:real_rows], np.asarray(single.peak))


def test_sharded_percentile_within_digest_error(fleet):
    values, counts = fleet
    mesh = make_mesh(data=4, time=2)
    sharded, real_rows = sharded_fleet_digest(SPEC, values, counts, mesh, chunk_size=512)

    estimate = sharded_percentile(SPEC, sharded, 99.0, real_rows)
    exact = np.asarray(masked_percentile(values.astype(np.float32), counts, 99.0))
    valid = counts > 0
    np.testing.assert_allclose(estimate[valid], exact[valid], rtol=SPEC.relative_error * 1.05)
    assert np.isnan(estimate[~valid]).all()

    peak = np.asarray(digest_ops.peak(sharded))[:real_rows]
    expected_peak = np.asarray(masked_max(values.astype(np.float32), counts))
    np.testing.assert_array_equal(peak[valid], expected_peak[valid])


def test_sharded_bisect_is_bit_exact(fleet):
    from krr_tpu.parallel import sharded_percentile_bisect

    values, counts = fleet
    exact = np.asarray(masked_percentile(values.astype(np.float32), counts, 99.0))
    for mesh_shape in [(8, 1), (4, 2), (1, 8)]:
        mesh = make_mesh(data=mesh_shape[0], time=mesh_shape[1])
        result = sharded_percentile_bisect(values, counts, 99.0, mesh)
        valid = counts > 0
        np.testing.assert_array_equal(result[valid], exact[valid])
        assert np.isnan(result[~valid]).all()


@pytest.mark.parametrize("mesh_shape", [(4, 2), (1, 8)])
def test_sharded_topk_is_bit_exact(fleet, mesh_shape):
    from krr_tpu.ops import topk_sketch as topk_ops
    from krr_tpu.parallel import sharded_fleet_topk

    values, counts = fleet
    mesh = make_mesh(data=mesh_shape[0], time=mesh_shape[1])
    k = topk_ops.required_k(values.shape[1], 99.0)
    sketch, real_rows = sharded_fleet_topk(values, counts, k, mesh, chunk_size=512)
    got = np.asarray(topk_ops.percentile(sketch, 99.0))[:real_rows]
    exact = np.asarray(masked_percentile(values.astype(np.float32), counts, 99.0))
    valid = counts > 0
    np.testing.assert_array_equal(got[valid], exact[valid])
    assert np.isnan(got[~valid]).all()
