"""Device-level compute observability: staged spans, compile accounting,
padding efficiency, and memory watermarks.

PR 4's telemetry stops at the host: a scan trace shows ``compute`` as one
opaque span, so "where does TPU time go" — compile vs execute, padding
waste, cache misses — was unanswerable. This module closes that gap with
four instruments, all wired through :class:`DeviceObs` (one per scan
session, injected into the strategy as ``strategy.obs``):

* **Stage spans** — :meth:`DeviceObs.stage` opens a child span of the
  active ``compute`` span for each compute leg (``pack`` → ``digest``/
  ``fold`` → ``quantile`` → ``round``). Spans measure WALL time, and JAX
  dispatch is asynchronous — a stage that merely enqueues device work would
  read as free while the next stage pays for it — so call sites fence
  results through :meth:`DeviceObs.fence` (``jax.block_until_ready``)
  before the span closes. Fencing serializes the dispatch pipeline, so it
  (like every instrument here that could perturb the hot path) only runs
  when the tracer is recording: with :data:`NULL_TRACER` a stage is the
  shared no-op context and ``fence`` is the identity.

* **Compile vs execute split** — ``jax.monitoring`` fires duration events
  for every jitted entry point's trace/lower/backend-compile phases and
  counting events for persistent-compilation-cache hits/misses
  (`krr_tpu.utils.compile_cache`). :func:`install_compile_hooks` registers
  one process-wide listener pair that (a) feeds the shared registry
  (``krr_tpu_compile_seconds{phase=…}``,
  ``krr_tpu_compile_cache_{hits,misses}_total``) and (b) advances a
  process-global compile clock. A recording stage reads the clock at
  enter/exit: a nonzero delta means this stage's wall includes a first-call
  compile, and the span gains ``compile_seconds`` / ``execute_seconds``
  attributes splitting the two. (The clock is process-global, so a
  concurrent compile on another thread would be attributed to whichever
  stage is open — scans serialize their device work, so in practice the
  open stage is the compiling one.)

* **Padding efficiency** — the packed ``[rows × capacity]`` matrix
  (`krr_tpu.ops.packing`) is mostly padding for ragged fleets;
  :meth:`DeviceObs.record_padding` turns a packed batch into
  ``krr_tpu_pad_waste_pct{resource=…}`` and
  ``krr_tpu_packed_elements{resource=…,kind=real|padding}`` gauges (a
  partition: the two kinds sum to the rectangular matrix). Cheap (one
  counts-sum per batch), so it fires on every mode, tracer or not.

* **Memory watermarks** — :meth:`DeviceObs.record_device_memory` snapshots
  each local device's ``memory_stats()`` (bytes in use / peak / limit)
  into ``krr_tpu_device_memory_bytes``; backends that report nothing (CPU)
  are a graceful no-op.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

from krr_tpu.obs.metrics import MetricsRegistry
from krr_tpu.obs.trace import NULL_TRACER, NullTracer

#: jax.monitoring counting events → our counters.
_EVENT_COUNTERS = {
    "/jax/compilation_cache/cache_hits": "krr_tpu_compile_cache_hits_total",
    "/jax/compilation_cache/cache_misses": "krr_tpu_compile_cache_misses_total",
}

#: jax.monitoring duration events → compile phases. Together these three
#: cover a jitted entry point's whole first-call cost; a persistent-cache
#: hit still pays trace+lower but skips backend_compile.
_DURATION_PHASES = {
    "/jax/core/compile/jaxpr_trace_duration": "trace",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "lower",
    "/jax/core/compile/backend_compile_duration": "backend_compile",
}

_hook_lock = threading.Lock()
_hooks_installed = False
#: The registry compile events currently land in. jax.monitoring listeners
#: cannot be unregistered, so ONE listener pair forwards to a swappable
#: target — last installer wins (each scan session installs its own
#: registry; in-process tests get deterministic counts the same way).
_target: Optional[MetricsRegistry] = None
#: Monotone total of compile seconds this process has spent — the clock
#: stage spans diff to attribute compile time. Guarded by the GIL (+= on a
#: float is not atomic across threads, but jax serializes compiles per
#: program and the worst case is a lost fraction of one phase).
_compile_seconds = 0.0


def compile_seconds_total() -> float:
    """Process-wide compile seconds so far (see the module docstring)."""
    return _compile_seconds


def _on_event(event: str, **_kwargs: Any) -> None:
    name = _EVENT_COUNTERS.get(event)
    target = _target
    if name is not None and target is not None:
        target.inc(name)


def _on_duration(event: str, duration: float, **_kwargs: Any) -> None:
    global _compile_seconds
    phase = _DURATION_PHASES.get(event)
    if phase is None:
        return
    _compile_seconds += duration
    target = _target
    if target is not None:
        target.observe("krr_tpu_compile_seconds", duration, phase=phase)


def install_compile_hooks(metrics: MetricsRegistry) -> None:
    """Route jax compile/cache monitoring events into ``metrics`` (and the
    process compile clock). Idempotent; safe when jax is absent or its
    monitoring API changes — compile telemetry is an optimization aid,
    never a scan-failure reason."""
    global _hooks_installed, _target
    _target = metrics
    with _hook_lock:
        if _hooks_installed:
            return
        try:
            from jax import monitoring
        except Exception:
            return
        try:
            monitoring.register_event_listener(_on_event)
            monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception:
            return
        _hooks_installed = True


class _Stage:
    """A recording compute stage: the underlying tracer span plus the
    compile-clock bracket that splits its wall into compile vs execute."""

    __slots__ = ("_ctx", "_t0", "_compile0")

    def __init__(self, ctx) -> None:
        self._ctx = ctx

    def __enter__(self):
        self._compile0 = compile_seconds_total()
        self._t0 = time.perf_counter()
        return self._ctx.__enter__()

    def __exit__(self, exc_type, exc, tb) -> bool:
        wall = time.perf_counter() - self._t0
        compiled = compile_seconds_total() - self._compile0
        if compiled > 0.0:
            self._ctx.span.set(
                compile_seconds=round(compiled, 6),
                execute_seconds=round(max(0.0, wall - compiled), 6),
            )
        return self._ctx.__exit__(exc_type, exc, tb)


class DeviceObs:
    """Per-session device-compute instrumentation (see module docstring).

    Always constructed with a REAL metrics registry (metrics are labeled
    dicts — cheap) but usually the no-op tracer: stage spans and fencing
    only activate when the tracer records, so the hot path stays untouched
    on the default CLI scan."""

    __slots__ = ("tracer", "metrics")

    def __init__(
        self, tracer: NullTracer = NULL_TRACER, metrics: Optional[MetricsRegistry] = None
    ) -> None:
        self.tracer = tracer
        self.metrics = metrics

    @property
    def enabled(self) -> bool:
        return self.tracer.enabled

    def stage(self, name: str, **attributes: Any):
        """A compute-stage span (child of the active ``compute`` span via
        contextvar propagation — including across ``asyncio.to_thread``).
        No-op (the shared null context, no allocation) when not recording."""
        if not self.tracer.enabled:
            return self.tracer.span(name, **attributes)
        return _Stage(self.tracer.span(name, **attributes))

    def fence(self, value):
        """``jax.block_until_ready`` when recording, identity otherwise —
        the dispatch fence that makes stage walls mean device time without
        serializing the pipeline on untraced scans."""
        if not self.tracer.enabled:
            return value
        try:
            import jax

            return jax.block_until_ready(value)
        except Exception:
            return value

    def record_padding(self, resource: str, packed) -> None:
        """Padding-efficiency gauges from one packed batch
        (`krr_tpu.ops.packing.padding_stats`)."""
        if self.metrics is None:
            return
        from krr_tpu.ops.packing import padding_stats

        real, total = padding_stats(packed.counts, packed.capacity)
        # A true partition: real + padding = the rectangular matrix the
        # device streams, so the two kinds sum meaningfully on a dashboard.
        self.metrics.set("krr_tpu_packed_elements", real, resource=resource, kind="real")
        self.metrics.set(
            "krr_tpu_packed_elements", total - real, resource=resource, kind="padding"
        )
        waste = 100.0 * (total - real) / total if total else 0.0
        self.metrics.set("krr_tpu_pad_waste_pct", waste, resource=resource)

    def record_device_memory(self) -> None:
        """Snapshot device memory watermarks where the backend reports them
        (``Device.memory_stats()``; CPU returns nothing — graceful no-op)."""
        if self.metrics is None:
            return
        try:
            import jax

            devices = jax.local_devices()
        except Exception:
            return
        for device in devices:
            try:
                stats = device.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            label = f"{device.platform}:{device.id}"
            for kind in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
                value = stats.get(kind)
                if value is not None:
                    self.metrics.set(
                        "krr_tpu_device_memory_bytes", value, device=label, kind=kind
                    )


#: The inert default every strategy carries until a scan session wires in
#: its own (`krr_tpu.core.runner.ScanSession`): null tracer, no registry.
NULL_DEVICE_OBS = DeviceObs()
