"""Key-range partitioning for the aggregation plane: the consistent ring.

``--federation-ring`` shards the AGGREGATOR, not the scanner: a shard
keeps scanning its clusters whole, but splits each tick's captured delta
ops by *owning aggregator* and streams every partition over its own
KRRFED1 connection with independent epoch watermarks. The mapping is a
classic consistent-hash ring — each aggregator node projects ``vnodes``
points onto a 64-bit circle (BLAKE2b of ``"{name}#{i}"``), and a key is
owned by the first node point at or clockwise past ``hash(key)``.

Why consistent hashing (and not modulo): adding or removing one node must
move ONLY the keys on the ranges that node gains or loses (≈ ``1/N`` of
the keyspace, spread across its vnodes) — every other key keeps its owner,
so its aggregator keeps its accumulated digest rows and epoch watermarks.
A modulo partition would reshuffle nearly every key on any resize,
forcing fleet-wide snapshot re-syncs. The stability property is pinned by
a join/leave test in ``tests/test_federation.py``.

Determinism: the hash is keyed on stable strings only (node names, object
keys), so every shard — and every future process — derives the identical
assignment from the identical ``--federation-ring`` flag. No coordination
service, no rebalance protocol: the flag IS the ring state.

A node spec may name standby endpoints (``name=host:port|host2:port2``):
the shard streams the node's partition to EVERY endpoint independently
(same records, same epochs — a replicated WAL on the wire), so a standby
aggregator holds the full key-range state and takes over on primary death
with zero lost epochs (each endpoint acks its own watermark; a lagging
endpoint that can no longer resume from the shard's pruned buffer falls
back to a snapshot re-sync).
"""

from __future__ import annotations

import hashlib
from bisect import bisect_right
from dataclasses import dataclass

import numpy as np

#: Ring points each node projects. 64 keeps the per-node keyspace share
#: within a few percent of 1/N at single-digit N without making the ring
#: build or the bisect lookups measurable.
DEFAULT_VNODES = 64


def _hash64(value: str) -> int:
    """Stable 64-bit ring position (BLAKE2b, process-independent)."""
    return int.from_bytes(
        hashlib.blake2b(value.encode("utf-8"), digest_size=8).digest(), "big"
    )


@dataclass(frozen=True)
class RingNode:
    """One aggregator in the ring: a stable name (the hash identity — the
    endpoints can move without moving keys) plus its endpoints, primary
    first, standbys after."""

    name: str
    endpoints: "tuple[tuple[str, int], ...]"


def parse_ring(value: str, flag: str = "--federation-ring") -> "list[RingNode]":
    """``name=host:port[|host:port...],name2=...`` → ring nodes. The NAME
    is the hash identity: re-pointing a node's endpoints (failover, pod
    reschedule) moves zero keys."""
    from krr_tpu.federation.shard import parse_endpoint

    nodes: "list[RingNode]" = []
    seen: "set[str]" = set()
    for spec in value.split(","):
        spec = spec.strip()
        if not spec:
            continue
        name, sep, endpoints_spec = spec.partition("=")
        name = name.strip()
        if not sep or not name:
            raise ValueError(
                f"{flag} entries must be name=host:port[|host:port...], got {spec!r}"
            )
        if name in seen:
            raise ValueError(f"{flag} names a node twice: {name!r}")
        seen.add(name)
        endpoints = tuple(
            parse_endpoint(endpoint.strip(), flag)
            for endpoint in endpoints_spec.split("|")
            if endpoint.strip()
        )
        if not endpoints:
            raise ValueError(f"{flag} node {name!r} names no endpoints")
        nodes.append(RingNode(name=name, endpoints=endpoints))
    if not nodes:
        raise ValueError(f"{flag} names no nodes")
    return nodes


class HashRing:
    """The key → aggregator-name assignment (bisect over sorted vnode
    points). Pure and immutable: shards rebuild one from the flag; tests
    build joined/left variants to pin the bounded-churn property."""

    def __init__(self, nodes: "list[RingNode]", *, vnodes: int = DEFAULT_VNODES) -> None:
        if not nodes:
            raise ValueError("a hash ring needs at least one node")
        self.nodes: "dict[str, RingNode]" = {node.name: node for node in nodes}
        points = sorted(
            (_hash64(f"{node.name}#{i}"), node.name)
            for node in nodes
            for i in range(int(vnodes))
        )
        self._hashes = [point for point, _ in points]
        self._names = [name for _, name in points]

    def owner(self, key: str) -> str:
        """The owning node NAME for ``key`` (first point clockwise)."""
        i = bisect_right(self._hashes, _hash64(key))
        return self._names[i if i < len(self._names) else 0]

    def spread(self, keys) -> "dict[str, int]":
        """Owned-key counts per node over ``keys`` (every node present,
        zero included) — the shard's ring-placement gauges."""
        counts = {name: 0 for name in self.nodes}
        for key in keys:
            counts[self.owner(key)] += 1
        return counts


def _gather_ranges(starts: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """Flat indices covering ``[starts[i], starts[i] + lengths[i])`` for
    every i, concatenated — the vectorized CSR row-subset gather."""
    total = int(lengths.sum())
    if total == 0:
        return np.empty(0, dtype=np.int64)
    offsets = np.repeat(np.cumsum(lengths) - lengths, lengths)
    return np.repeat(starts, lengths) + (np.arange(total, dtype=np.int64) - offsets)


def partition_ops(ops: list, owner_of) -> "dict[str, list]":
    """Split captured store ops (`DigestStore.pending_ops` shapes) by
    owning node. Row slices are plain fancy-index copies of the same
    float32 values, so folding each partition into its own store and
    unioning the stores is bit-identical to folding the unsplit ops into
    one store (per-key row order within a record is preserved; digest
    folds are per-row adds/maxes with no cross-row coupling).

    Requires every op to carry its key list (shards run with
    ``capture_full_keys`` on — a keys-elided whole-store fold cannot be
    partitioned because its row meaning lives in the TARGET store).
    """
    out: "dict[str, list]" = {}
    for op in ops:
        kind, keys = op[0], op[1]
        if keys is None:
            raise ValueError(
                "ring partitioning requires captured key lists "
                "(DigestStore.capture_full_keys) — got a keys-elided fold"
            )
        groups: "dict[str, list[int]]" = {}
        for i, key in enumerate(keys):
            groups.setdefault(owner_of(key), []).append(i)
        if kind in ("grow", "drop"):
            for name, idx in groups.items():
                out.setdefault(name, []).append((kind, [keys[i] for i in idx]))
        elif kind == "fold":
            _, _, cpu_counts, cpu_total, cpu_peak, mem_total, mem_peak = op
            for name, idx in groups.items():
                rows = np.asarray(idx, dtype=np.int64)
                out.setdefault(name, []).append(
                    (
                        "fold",
                        [keys[i] for i in idx],
                        np.asarray(cpu_counts)[rows],
                        np.asarray(cpu_total)[rows],
                        np.asarray(cpu_peak)[rows],
                        np.asarray(mem_total)[rows],
                        np.asarray(mem_peak)[rows],
                    )
                )
        elif kind == "fold_csr":
            _, _, vals, cols, indptr, cpu_total, cpu_peak, mem_total, mem_peak = op
            indptr = np.asarray(indptr)
            lengths_all = np.diff(indptr)
            for name, idx in groups.items():
                rows = np.asarray(idx, dtype=np.int64)
                lengths = lengths_all[rows].astype(np.int64, copy=False)
                flat = _gather_ranges(indptr[:-1][rows].astype(np.int64), lengths)
                sub_indptr = np.concatenate(
                    [np.zeros(1, dtype=np.int64), np.cumsum(lengths)]
                ).astype(indptr.dtype, copy=False)
                out.setdefault(name, []).append(
                    (
                        "fold_csr",
                        [keys[i] for i in idx],
                        np.asarray(vals)[flat],
                        np.asarray(cols)[flat],
                        sub_indptr,
                        np.asarray(cpu_total)[rows],
                        np.asarray(cpu_peak)[rows],
                        np.asarray(mem_total)[rows],
                        np.asarray(mem_peak)[rows],
                    )
                )
        else:
            raise ValueError(f"unknown captured op kind {kind!r}")
    return out
