# Example: creating your own strategy plugin.
#
# Defining the subclass registers it; running this file adds a
# `spikeguard` sub-command to the CLI:
#
#     python ./custom_strategy.py spikeguard --cpu_percentile 95 --spike_guard 60
#
# The scenario: a latency-sensitive service whose p95 usage is low but which
# takes short request bursts. A plain p95 request starves the bursts, a
# plain-max request wastes quota — so this strategy recommends the p95
# *floored at a fraction of the observed peak* ("never give the container
# less than 60% of what its worst burst actually used"), and sizes memory at
# the peak plus a fixed per-pod slack for connection buffers.

import os
import sys
from decimal import Decimal

import pydantic as pd

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # run from a checkout

import krr_tpu
from krr_tpu.api.models import HistoryData, K8sObjectData, ResourceRecommendation, ResourceType, RunResult
from krr_tpu.api.strategies import BaseStrategy, StrategySettings


# Field descriptions become CLI `--flag` help text.
class SpikeGuardStrategySettings(StrategySettings):
    cpu_percentile: Decimal = pd.Field(
        95, gt=0, le=100, description="Steady-state CPU percentile before the spike floor."
    )
    spike_guard: Decimal = pd.Field(
        60, ge=0, le=100, description="CPU request is never below this percent of the observed peak."
    )
    memory_slack_mb: Decimal = pd.Field(
        64, ge=0, description="Flat memory slack added on top of the observed peak, in MB."
    )


def _flat_sorted(samples_by_pod: "dict[str, list[Decimal]]") -> "list[Decimal]":
    return sorted(s for pod_samples in samples_by_pod.values() for s in pod_samples)


class SpikeGuardStrategy(BaseStrategy[SpikeGuardStrategySettings]):
    """p-th percentile CPU with a peak-fraction floor; peak-plus-slack memory."""

    __display_name__ = "spikeguard"

    def run(self, history_data: HistoryData, object_data: K8sObjectData) -> RunResult:
        cpu = _flat_sorted(history_data.get(ResourceType.CPU, {}))
        mem = _flat_sorted(history_data.get(ResourceType.Memory, {}))

        if cpu:
            steady = cpu[int((len(cpu) - 1) * self.settings.cpu_percentile / 100)]
            floor = cpu[-1] * self.settings.spike_guard / 100
            cpu_request = max(steady, floor)
        else:
            cpu_request = Decimal("nan")

        if mem:
            mem_request = mem[-1] + self.settings.memory_slack_mb * 1_000_000
        else:
            mem_request = Decimal("nan")

        return {
            ResourceType.CPU: ResourceRecommendation(request=cpu_request, limit=None),
            ResourceType.Memory: ResourceRecommendation(request=mem_request, limit=mem_request),
        }


if __name__ == "__main__":
    krr_tpu.run()
