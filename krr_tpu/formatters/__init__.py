from krr_tpu.formatters.base import BaseFormatter
from krr_tpu.formatters.machine import JSONFormatter, PPrintFormatter, YAMLFormatter
from krr_tpu.formatters.table import TableFormatter

__all__ = ["BaseFormatter", "JSONFormatter", "PPrintFormatter", "YAMLFormatter", "TableFormatter"]
