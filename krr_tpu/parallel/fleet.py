"""Sharded fleet reductions: the multi-device digest build.

This is the TPU-native replacement for the reference's per-object asyncio
fan-out (SURVEY.md §2.9): the packed ``[N, T]`` fleet matrix is laid out over
a ``(data, time)`` mesh — containers sharded over ``data``, timesteps over
``time`` — each device builds a digest of its local block, and the digests
merge with ``psum``/``pmax`` collectives *along the time axis only* (digest
merges are associative adds, so the collective is exact, not approximate).
After the merge every row's digest lives replicated along time and sharded
along data, so quantile extraction is embarrassingly parallel.

Host→device padding: rows pad with count-0 entries (they produce NaN → sliced
off), time pads with zeros beyond each row's count (masked out by the global
position test inside each shard).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec

from krr_tpu.ops import digest as digest_ops
from krr_tpu.ops import selection
from krr_tpu.ops import topk_sketch as topk_ops
from krr_tpu.ops.digest import Digest, DigestSpec
from krr_tpu.ops.topk_sketch import TopKSketch
from krr_tpu.parallel.mesh import DATA_AXIS, TIME_AXIS, fleet_sharding, fleet_spec, rows_sharding, rows_spec


def shard_map_compat(**kwargs):
    """``jax.shard_map`` decorator across JAX versions: new JAX exposes it
    top-level with ``check_vma``; older releases (≤ 0.4.x) ship
    ``jax.experimental.shard_map.shard_map`` with the same knob named
    ``check_rep``. The kernels themselves are version-agnostic."""
    if hasattr(jax, "shard_map"):
        return partial(jax.shard_map, **kwargs)
    from jax.experimental.shard_map import shard_map

    kwargs["check_rep"] = kwargs.pop("check_vma")
    return partial(shard_map, **kwargs)


def pad_for_mesh(values: np.ndarray, counts: np.ndarray, mesh: Mesh) -> tuple[np.ndarray, np.ndarray, int]:
    """Pad rows/time so both axes divide the mesh; returns (values, counts, real_rows)."""
    n, t = values.shape
    data_size = mesh.shape[DATA_AXIS]
    time_size = mesh.shape[TIME_AXIS]
    row_pad = (-n) % data_size
    time_pad = (-t) % time_size
    if row_pad or time_pad:
        values = np.pad(values, ((0, row_pad), (0, time_pad)))
        counts = np.pad(counts, (0, row_pad))
    return values, counts, n


def transfer_to_mesh(
    values: np.ndarray, counts: np.ndarray, mesh: Mesh
) -> tuple[jax.Array, jax.Array, int]:
    """Pad + cast on host, then shard host→device directly.

    The cast happens in numpy and the float32 host array goes straight into
    ``jax.device_put`` with the target sharding — routing through a device
    array first would stage the full matrix on one device before resharding,
    which is exactly the OOM the mesh exists to avoid.
    """
    values, counts, real_rows = pad_for_mesh(values, counts, mesh)
    values_d = jax.device_put(np.ascontiguousarray(values, dtype=np.float32), fleet_sharding(mesh))
    counts_d = jax.device_put(np.ascontiguousarray(counts, dtype=np.int32), rows_sharding(mesh))
    return values_d, counts_d, real_rows


@partial(jax.jit, static_argnames=("spec", "mesh", "chunk_size"))
def _sharded_digest_build(
    spec: DigestSpec, mesh: Mesh, values: jax.Array, counts: jax.Array, chunk_size: int
) -> Digest:
    @shard_map_compat(
        mesh=mesh,
        in_specs=(fleet_spec(), rows_spec()),
        out_specs=(rows_spec(), rows_spec(), rows_spec()),
        check_vma=False,
    )
    def build(local_values: jax.Array, local_counts: jax.Array):
        # Global time offset of this shard's block: validity is decided against
        # the row's total count, not the local width.
        t_local = local_values.shape[1]
        offset = jax.lax.axis_index(TIME_AXIS) * t_local
        local = digest_ops.build_from_packed(
            spec, local_values, local_counts, chunk_size=min(chunk_size, t_local), time_offset=offset
        )
        # Exact merge across the time axis (counts add; peak is a max).
        merged_counts = jax.lax.psum(local.counts, TIME_AXIS)
        merged_total = jax.lax.psum(local.total, TIME_AXIS)
        merged_peak = jax.lax.pmax(local.peak, TIME_AXIS)
        return merged_counts, merged_total, merged_peak

    bucket_counts, total, peak = build(values, counts)
    return Digest(counts=bucket_counts, total=total, peak=peak)


def sharded_fleet_digest(
    spec: DigestSpec,
    values: np.ndarray,
    counts: np.ndarray,
    mesh: Mesh,
    chunk_size: int = 8192,
) -> tuple[Digest, int]:
    """Build the fleet digest over a mesh. Returns (digest, real_row_count) —
    the digest's leading axis may be padded to the mesh shape."""
    values_d, counts_d, real_rows = transfer_to_mesh(values, counts, mesh)
    return _sharded_digest_build(spec, mesh, values_d, counts_d, chunk_size), real_rows


def sharded_percentile(
    spec: DigestSpec, digest: Digest, q: float, real_rows: int
) -> np.ndarray:
    """Quantile extraction over the sharded digest (row-parallel, no collectives),
    sliced back to the real row count on host."""
    return np.asarray(digest_ops.percentile(spec, digest, q))[:real_rows]


@partial(jax.jit, static_argnames=("mesh", "k", "chunk_size"))
def _sharded_topk_build(
    mesh: Mesh, values: jax.Array, counts: jax.Array, k: int, chunk_size: int
) -> TopKSketch:
    @shard_map_compat(
        mesh=mesh,
        in_specs=(fleet_spec(), rows_spec()),
        out_specs=(PartitionSpec(DATA_AXIS, None), rows_spec()),
        check_vma=False,
    )
    def build(local_values: jax.Array, local_counts: jax.Array):
        t_local = local_values.shape[1]
        offset = jax.lax.axis_index(TIME_AXIS) * t_local
        local = topk_ops.build_from_packed(
            local_values, local_counts, k=k, chunk_size=min(chunk_size, t_local), time_offset=offset
        )
        # Exact merge across the time shards: the union's top-K is inside the
        # gathered per-shard top-Ks, so one all_gather + top_k finishes it.
        gathered = jax.lax.all_gather(local.values, TIME_AXIS, axis=1, tiled=True)
        top, _ = jax.lax.top_k(gathered, k)
        return top, jax.lax.psum(local.total, TIME_AXIS)

    top, total = build(values, counts)
    return TopKSketch(values=top, total=total)


def sharded_fleet_topk(
    values: np.ndarray,
    counts: np.ndarray,
    k: int,
    mesh: Mesh,
    chunk_size: int = 8192,
) -> tuple[TopKSketch, int]:
    """Build the exact top-K sketch over the mesh (the sequence-parallel form
    of `krr_tpu.ops.topk_sketch`). Returns (sketch, real_row_count)."""
    values_d, counts_d, real_rows = transfer_to_mesh(values, counts, mesh)
    return _sharded_topk_build(mesh, values_d, counts_d, k, chunk_size), real_rows


@partial(jax.jit, static_argnames=("mesh",))
def _sharded_max_build(mesh: Mesh, values: jax.Array, counts: jax.Array) -> jax.Array:
    @shard_map_compat(
        mesh=mesh,
        in_specs=(fleet_spec(), rows_spec()),
        out_specs=rows_spec(),
        check_vma=False,
    )
    def build(local_values: jax.Array, local_counts: jax.Array) -> jax.Array:
        t_local = local_values.shape[1]
        offset = jax.lax.axis_index(TIME_AXIS) * t_local
        position = jnp.arange(t_local, dtype=jnp.int32)[None, :] + offset
        valid = position < local_counts[:, None]
        local_peak = jnp.max(jnp.where(valid, local_values, -jnp.inf), axis=1)
        return jax.lax.pmax(local_peak, TIME_AXIS)

    peak = build(values, counts)
    return jnp.where(counts > 0, peak, jnp.nan)


def sharded_masked_max(
    values: np.ndarray, counts: np.ndarray, mesh: Mesh
) -> np.ndarray:
    """Exact per-row max over the mesh (memory recommendations): local masked
    max then a pmax along the time axis."""
    values_d, counts_d, real_rows = transfer_to_mesh(values, counts, mesh)
    return np.asarray(_sharded_max_build(mesh, values_d, counts_d))[:real_rows]


@partial(jax.jit, static_argnames=("mesh", "num_iters"))
def _sharded_bisect_build(
    mesh: Mesh, values: jax.Array, counts: jax.Array, q: jax.Array, num_iters: int = 31
) -> jax.Array:
    @shard_map_compat(
        mesh=mesh,
        in_specs=(fleet_spec(), rows_spec(), PartitionSpec()),
        out_specs=rows_spec(),
        check_vma=False,
    )
    def run(local_values: jax.Array, local_counts: jax.Array, q_val: jax.Array) -> jax.Array:
        t_local = local_values.shape[1]
        offset = jax.lax.axis_index(TIME_AXIS) * t_local
        position = jnp.arange(t_local, dtype=jnp.int32)[None, :] + offset
        mask = position < local_counts[:, None]
        # Same core as the single-device path; the only difference is the
        # count reduction — an exact integer psum across the time shards.
        return selection.bisect_loop(
            selection.as_ordered_bits(local_values),
            mask,
            selection.selection_rank(local_counts, q_val),
            count_reduce=lambda le: jax.lax.psum(le, TIME_AXIS),
            num_iters=num_iters,
        )

    result = run(values, counts, jnp.float32(q))
    return jnp.where(counts > 0, result, jnp.nan)


def sharded_percentile_bisect(
    values: np.ndarray, counts: np.ndarray, q: float, mesh: Mesh
) -> np.ndarray:
    """Exact per-row percentile over the mesh via bit-space bisection
    (`krr_tpu.ops.selection`): 31 counting passes, each reduced with an exact
    integer psum along the time axis — bit-identical to the single-device
    sort/bisect paths, but sequence-parallel."""
    values_d, counts_d, real_rows = transfer_to_mesh(values, counts, mesh)
    return np.asarray(_sharded_bisect_build(mesh, values_d, counts_d, q))[:real_rows]
